"""Telemetry subsystem tests (ISSUE 5): registry thread-safety and
percentiles, sampled emit cadence, engine-loop span integration,
watchdog fire-and-dump (+ SIGTERM forensics), Prometheus rendering,
trace_report over a committed mini JSONL, and the MetricsLogger
satellites (non-finite JSON, context manager, TB step carry-forward)."""

import json
import os
import signal
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_vit_paper_replication_tpu.metrics import MetricsLogger
from pytorch_vit_paper_replication_tpu.telemetry import (
    INSTRUMENTS, ROW_KEYS, StepTelemetry, TelemetryRegistry, Watchdog)

REPO = Path(__file__).resolve().parent.parent
MINI_JSONL = Path(__file__).parent / "data" / "telemetry_mini.jsonl"


# ------------------------------------------------------------- registry
def test_registry_thread_safety():
    """Counters/histograms under 8 writer threads lose no updates."""
    reg = TelemetryRegistry()
    n_threads, n_each = 8, 500

    def work(tid):
        for i in range(n_each):
            reg.count("tel_steps_total")
            reg.count("tel_images_total", 4)
            reg.observe("tel_step_s", (tid * n_each + i) % 97 / 1000)
            reg.gauge("tel_goodput_pct", tid)

    threads = [threading.Thread(target=work, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    assert snap["counters"]["tel_steps_total"] == n_threads * n_each
    assert snap["counters"]["tel_images_total"] == n_threads * n_each * 4
    assert snap["histograms"]["tel_step_s"]["count_total"] \
        == n_threads * n_each
    assert snap["gauges"]["tel_goodput_pct"] in range(n_threads)


def test_registry_histogram_percentiles():
    reg = TelemetryRegistry()
    for v in range(1, 1001):           # 1..1000 ms
        reg.observe("lat", v / 1000.0)
    h = reg.snapshot()["histograms"]["lat"]
    assert h["p50"] == pytest.approx(0.5005, abs=0.01)
    assert h["p95"] == pytest.approx(0.95, abs=0.01)
    assert h["p99"] == pytest.approx(0.99, abs=0.01)
    assert h["count"] == 1000 and h["count_total"] == 1000
    # Window is bounded: a long run cannot grow memory.
    reg2 = TelemetryRegistry(hist_window=16)
    for v in range(1000):
        reg2.observe("lat", float(v))
    h2 = reg2.snapshot()["histograms"]["lat"]
    assert h2["count"] == 16 and h2["count_total"] == 1000
    assert h2["p50"] >= 984  # only the newest window remains


def test_registry_event_ring_bounded():
    reg = TelemetryRegistry(event_ring=8)
    for i in range(20):
        reg.event("step", i=i)
    events = reg.last_events()
    assert len(events) == 8
    assert events[-1]["i"] == 19 and events[0]["i"] == 12


def test_prometheus_render_shape():
    reg = TelemetryRegistry()
    reg.count("tel_steps_total", 3)
    reg.gauge("tel_goodput_pct", 91.5)
    reg.gauge("weird name!", 1.0)       # sanitized, not dropped
    reg.gauge("nonnum", "skipme")       # non-numeric gauges are skipped
    for v in (0.1, 0.2, 0.3):
        reg.observe("tel_step_s", v)
    text = reg.to_prometheus()
    assert "# TYPE vit_tel_steps_total counter\nvit_tel_steps_total 3" \
        in text
    assert "# TYPE vit_tel_goodput_pct gauge\nvit_tel_goodput_pct 91.5" \
        in text
    assert "vit_weird_name_ 1" in text
    assert "skipme" not in text
    assert "# TYPE vit_tel_step_s summary" in text
    assert 'vit_tel_step_s{quantile="0.5"} 0.2' in text
    assert "vit_tel_step_s_count 3" in text
    assert text.endswith("\n")


# ------------------------------------------------------ sampled cadence
def test_step_telemetry_sampled_emit_cadence(tmp_path):
    """sample_every=4 over 10 steps -> exactly 3 'step' rows (steps
    1, 5, 9) plus the epoch_summary row; every row is valid JSON."""
    reg = TelemetryRegistry()
    tel = StepTelemetry(tmp_path / "t.jsonl", registry=reg,
                        sample_every=4, n_chips=1)
    for i in range(10):
        tel.step(data_wait_s=0.002, exec_s=0.01, images=8,
                 step=i + 1, epoch=1)
    tel.epoch_end(epoch=1, step=10)
    tel.close()
    rows = [json.loads(line) for line in
            (tmp_path / "t.jsonl").read_text().splitlines()]
    steps = [r for r in rows if r["event"] == "step"]
    assert len(steps) == 3
    assert [r["step"] for r in steps] == [1, 5, 9]
    summaries = [r for r in rows if r["event"] == "epoch_summary"]
    assert len(summaries) == 1
    s = summaries[0]
    assert s["tel_steps"] == 10 and s["tel_images"] == 80
    # Registry saw EVERY step, not just the sampled ones.
    assert reg.snapshot()["counters"]["tel_steps_total"] == 10
    # should_block follows block_every (defaults to sample_every).
    assert tel.should_block() is False
    tel2 = StepTelemetry(registry=TelemetryRegistry(), sample_every=1,
                         n_chips=1)
    assert tel2.should_block() is True


def test_step_telemetry_epoch_summary_math(tmp_path):
    """Goodput/data-wait fractions come from the recorded spans over
    the real epoch wall; percentiles from the step walls."""
    reg = TelemetryRegistry()
    tel = StepTelemetry(registry=reg, sample_every=100, n_chips=1)
    t0 = time.perf_counter()
    for _ in range(5):
        tel.step(data_wait_s=0.004, exec_s=0.016, images=4)
        time.sleep(0.02)
    tel.span("eval", 0.05)
    wall = time.perf_counter() - t0
    s = tel.epoch_end(epoch=1)
    assert s["tel_step_p50_s"] == pytest.approx(0.02, abs=1e-6)
    assert s["tel_epoch_wall_s"] == pytest.approx(wall, abs=0.05)
    expect_goodput = 100 * 5 * 0.016 / s["tel_epoch_wall_s"]
    assert s["tel_goodput_pct"] == pytest.approx(expect_goodput, rel=0.05)
    assert s["tel_data_wait_frac"] == pytest.approx(
        5 * 0.004 / s["tel_epoch_wall_s"], rel=0.05)
    assert s["tel_eval_s_sum"] == pytest.approx(0.05)
    with pytest.raises(ValueError, match="unknown span"):
        tel.span("lunch", 1.0)


def test_step_telemetry_amortizes_async_barrier_windows():
    """Under async dispatch the unbarriered walls are dispatch times
    and the barriered step absorbs the window's backlog — neither is a
    per-step truth. The histograms/percentiles get the window-amortized
    value; a one-step window (step 1's compile) keeps full magnitude
    (review r9)."""
    reg = TelemetryRegistry()
    tel = StepTelemetry(registry=reg, sample_every=4, n_chips=1)
    tel.step(data_wait_s=0.0, exec_s=4.0, images=8, blocked=True)
    for _ in range(3):                       # async: dispatch-only walls
        tel.step(data_wait_s=0.0, exec_s=0.001, images=8, blocked=False)
    tel.step(data_wait_s=0.0, exec_s=0.997, images=8, blocked=True)
    s = tel.epoch_end(epoch=1)
    # Window of 4 amortizes to 0.25/step; step-1 compile stays 4.0.
    assert s["tel_step_p50_s"] == pytest.approx(0.25, abs=1e-6)
    assert s["tel_step_p99_s"] == pytest.approx(4.0, rel=0.05)
    hist = reg.snapshot()["histograms"]["tel_step_s"]
    assert hist["count_total"] == 5
    assert hist["p50"] == pytest.approx(0.25, abs=1e-6)


# ------------------------------------------------------ engine integration
def test_engine_train_emits_telemetry(tiny_config, tmp_path):
    """The instrumented engine loop splits step wall into data-wait vs
    exec, records the eval span, and closes each epoch with a summary
    whose accounting covers the epoch wall."""
    from pytorch_vit_paper_replication_tpu import engine
    from pytorch_vit_paper_replication_tpu.configs import TrainConfig
    from pytorch_vit_paper_replication_tpu.data import synthetic_batch
    from pytorch_vit_paper_replication_tpu.models import ViT
    from pytorch_vit_paper_replication_tpu.optim import make_optimizer

    model = ViT(tiny_config)
    rng = jax.random.key(0)
    x = jnp.zeros((1, tiny_config.image_size, tiny_config.image_size, 3))
    params = model.init(rng, x)["params"]
    state = engine.TrainState.create(
        apply_fn=model.apply, params=params,
        tx=make_optimizer(TrainConfig(), 8), rng=rng)
    batches = [jax.tree.map(jnp.asarray, synthetic_batch(
        8, tiny_config.image_size, tiny_config.num_classes, seed=s))
        for s in range(3)]

    def slow_batches():
        for b in batches:
            time.sleep(0.01)      # visible data-wait
            yield b

    reg = TelemetryRegistry()
    with StepTelemetry(tmp_path / "t.jsonl", registry=reg,
                       sample_every=2, n_chips=1) as tel:
        engine.train(state, slow_batches, lambda: iter(batches[:1]),
                     epochs=2, verbose=False, telemetry=tel)
    rows = [json.loads(line) for line in
            (tmp_path / "t.jsonl").read_text().splitlines()]
    summaries = [r for r in rows if r["event"] == "epoch_summary"]
    assert len(summaries) == 2
    for s in summaries:
        assert s["tel_steps"] == 3
        assert s["tel_data_wait_s_sum"] >= 0.025   # 3 x 10ms sleeps
        assert s["tel_eval_s_sum"] > 0
        assert 0 < s["tel_goodput_pct"] <= 100
        assert 0 <= s["tel_data_wait_frac"] < 1
    step_rows = [r for r in rows if r["event"] == "step"]
    assert step_rows and all("tel_step_exec_s" in r for r in step_rows)
    # The sampled honesty barrier fired (block_every = sample_every = 2).
    assert any(r["tel_block_sampled"] for r in step_rows)
    hist = reg.snapshot()["histograms"]
    assert hist["tel_step_s"]["count_total"] == 6
    assert hist["tel_eval_s"]["count_total"] == 2


# -------------------------------------------------------------- watchdog
def test_watchdog_fires_and_dumps_postmortem(tmp_path):
    """A stalled loop (no beats inside the deadline) produces a
    postmortem containing all-thread stacks, memory, and the last
    telemetry events — the diagnostics a silent freeze never leaves."""
    reg = TelemetryRegistry()
    reg.event("step", step=41)
    reg.event("span", span="checkpoint", seconds=1.5)
    pm = tmp_path / "pm.txt"
    wd = Watchdog(0.2, postmortem_path=pm, registry=reg, poll_s=0.05)
    wd.start()
    try:
        deadline = time.time() + 5.0
        while not pm.exists() and time.time() < deadline:
            time.sleep(0.05)
    finally:
        wd.stop()
    text = pm.read_text()
    assert "watchdog postmortem reason=stall" in text
    # faulthandler stacks: this (main) thread appears mid-sleep/join.
    assert "all-thread stacks" in text and "Thread" in text
    assert "test_telemetry" in text or "File" in text
    assert "---- memory ----" in text and "host" in text
    # The event ring rode along — the run's last actions are in the dump.
    assert '"event": "span"' in text and "checkpoint" in text
    assert reg.snapshot()["counters"]["watchdog_stalls_total"] == 1
    assert reg.snapshot()["counters"]["watchdog_postmortems_total"] == 1


def test_watchdog_beats_prevent_dump_and_rearm(tmp_path):
    reg = TelemetryRegistry()
    pm = tmp_path / "pm.txt"
    wd = Watchdog(0.4, postmortem_path=pm, registry=reg, poll_s=0.05)
    wd.start()
    try:
        for _ in range(8):
            wd.beat()
            time.sleep(0.05)
        assert not pm.exists()           # steady beats: no stall
        time.sleep(0.8)                  # stall once
        assert pm.exists()
        wd.beat()                        # recovery re-arms
        time.sleep(0.8)                  # stall AGAIN
    finally:
        wd.stop()
    assert reg.snapshot()["counters"]["watchdog_stalls_total"] == 2
    assert pm.read_text().count("== end postmortem ==") == 2


def test_watchdog_first_beat_grace(tmp_path):
    """Until the first beat the deadline is the startup grace — step 1
    includes the full XLA compile, and that is startup, not a stall
    (review r9). After the grace expires with still no beat, the dump
    fires."""
    reg = TelemetryRegistry()
    pm = tmp_path / "pm.txt"
    wd = Watchdog(0.1, postmortem_path=pm, registry=reg, poll_s=0.03,
                  first_grace_s=0.8)
    wd.start()
    try:
        time.sleep(0.4)                  # > deadline, < grace: healthy
        assert not pm.exists()
        deadline = time.time() + 5.0     # grace expiry: NOW it's a stall
        while not pm.exists() and time.time() < deadline:
            time.sleep(0.05)
        assert pm.exists()
    finally:
        wd.stop()


def test_watchdog_sigterm_dump_chains_previous_handler(tmp_path):
    """SIGTERM (preemption) dumps forensics, then the process still
    sees the previously-installed disposition."""
    reg = TelemetryRegistry()
    reg.event("step", step=7)
    pm = tmp_path / "pm.txt"
    seen = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    try:
        wd = Watchdog(60.0, postmortem_path=pm, registry=reg)
        wd.install_sigterm()
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5.0
        while not seen and time.time() < deadline:
            time.sleep(0.01)
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert seen == [signal.SIGTERM]      # chained disposition ran
    text = pm.read_text()
    assert "reason=sigterm" in text
    assert '"step": 7' in text


# ----------------------------------------------------------- trace_report
def _load_trace_report():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "trace_report", REPO / "tools" / "trace_report.py")
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)
    return tr


def test_trace_report_smoke_over_committed_mini_jsonl():
    """The committed fixture renders: per-epoch table + phase bars."""
    tr = _load_trace_report()
    events = tr.load_events(MINI_JSONL)
    assert events, "committed fixture missing/empty"
    report = tr.build_report(events, source="telemetry_mini.jsonl")
    assert "phase breakdown" in report
    assert "device compute" in report and "data wait" in report
    assert "goodput" in report
    # Both fixture epochs appear with their step counts.
    assert "\n    1 " in report and "\n    2 " in report


def test_trace_report_tolerates_foreign_and_torn_rows(tmp_path):
    """Train-metric rows, serve rows, and a torn final line must not
    break the report (the streams share one file grammar)."""
    tr = _load_trace_report()
    p = tmp_path / "mix.jsonl"
    p.write_text(
        json.dumps({"time": 1.0, "step": 5, "train_loss": 0.5}) + "\n"
        + json.dumps({"time": 2.0, "event": "step", "tel_step_s": 0.1,
                      "tel_data_wait_s": 0.02, "tel_step_exec_s": 0.08,
                      "step": 5, "epoch": 1}) + "\n"
        + '{"torn": tru')
    report = tr.build_report(tr.load_events(p), source="mix")
    assert "synthesized" in report       # no epoch_summary -> fallback
    assert "phase breakdown" in report


def test_trace_report_empty_stream(tmp_path):
    tr = _load_trace_report()
    p = tmp_path / "empty.jsonl"
    p.write_text("")
    assert "no telemetry rows" in tr.build_report(tr.load_events(p))


def test_trace_report_partial_epoch_tail_not_dropped(tmp_path):
    """Step rows AFTER the last epoch_summary (run killed mid-epoch
    N>1) must appear as a synthesized final row — those trailing steps
    are the forensic window right before the kill (review r9)."""
    tr = _load_trace_report()
    p = tmp_path / "killed.jsonl"
    summary = {"time": 10.0, "event": "epoch_summary", "epoch": 1,
               "tel_steps": 2, "tel_images": 16,
               "tel_epoch_wall_s": 1.0, "tel_step_p50_s": 0.1,
               "tel_step_p95_s": 0.2, "tel_step_p99_s": 0.2,
               "tel_data_wait_frac": 0.01, "tel_goodput_pct": 90.0,
               "tel_images_per_sec": 16.0, "tel_data_wait_s_sum": 0.01,
               "tel_step_exec_s_sum": 0.9, "tel_ckpt_s_sum": 0.0,
               "tel_eval_s_sum": 0.05}
    tail_step = {"time": 11.0, "event": "step", "tel_step_s": 0.5,
                 "tel_data_wait_s": 0.1, "tel_step_exec_s": 0.4,
                 "step": 3, "epoch": 2}
    p.write_text(json.dumps(summary) + "\n" + json.dumps(tail_step) + "\n")
    report = tr.build_report(tr.load_events(p), source="killed")
    assert "partial epoch" in report
    # Epoch 1's row AND the synthesized '-' tail row both render, and
    # the tail's wall is in the run total (1.0 + 0.5).
    assert "\n    1 " in report and "\n    - " in report
    assert "1.50s" in report


# ------------------------------------------------- MetricsLogger satellites
def test_metrics_logger_nonfinite_floats_stay_valid_json(tmp_path):
    """NaN -> null, +/-Inf -> signed strings: every emitted line parses
    under strict JSON (json.dumps used to write bare NaN/Infinity)."""
    path = tmp_path / "m.jsonl"
    with MetricsLogger(path) as logger:
        logger.log(step=1, loss=float("nan"), peak=float("inf"),
                   trough=float("-inf"), ok=0.5)
        logger.log(step=2, loss=jnp.float32(float("nan")))  # device scalar
    lines = path.read_text().splitlines()
    records = [json.loads(line) for line in lines]   # strict parse
    assert records[0]["loss"] is None
    assert records[0]["peak"] == "Infinity"
    assert records[0]["trough"] == "-Infinity"
    assert records[0]["ok"] == 0.5
    assert records[1]["loss"] is None
    for line in lines:
        assert "NaN" not in line and "Infinity" not in line.replace(
            '"Infinity"', "").replace('"-Infinity"', "")


def test_metrics_logger_context_manager_closes_on_raise(tmp_path):
    path = tmp_path / "m.jsonl"
    with pytest.raises(RuntimeError, match="boom"):
        with MetricsLogger(path) as logger:
            logger.log(step=1, loss=1.0)
            raise RuntimeError("boom")
    assert logger._fh is None            # handle closed on the raise path
    assert json.loads(path.read_text().splitlines()[0])["loss"] == 1.0


def test_metrics_logger_tb_step_carry_forward(tmp_path, monkeypatch):
    """Rows without a step key inherit the last-seen step instead of
    collapsing onto global_step=0."""
    calls = []

    class FakeTB:
        def __init__(self, d):
            pass

        def add_scalar(self, k, v, global_step):
            calls.append((k, v, global_step))

        def flush(self):
            pass

        def close(self):
            pass

    import tensorboardX
    monkeypatch.setattr(tensorboardX, "SummaryWriter", FakeTB)
    with MetricsLogger(tb_dir=tmp_path / "tb") as logger:
        logger.log(step=5, a=1.0)
        logger.log(b=2.0)                # no step: inherits 5, not 0
        logger.log(step=9, c=3.0)
        logger.log(d=4.0)                # inherits 9
    assert calls == [("a", 1.0, 5), ("b", 2.0, 5),
                     ("c", 3.0, 9), ("d", 4.0, 9)]


# ------------------------------------------------------- overhead harness
@pytest.mark.slow
def test_telemetry_overhead_harness(tmp_path):
    """The full A/B at reduced scale: result shape + a sane measurement
    (the committed-evidence path; the 2% verdict is bench.py's gate)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "telemetry_overhead", REPO / "tools" / "telemetry_overhead.py")
    to = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(to)
    result = to.run_overhead(steps=8, reps=1, batch_size=4,
                             workdir=tmp_path)
    assert result["telemetry_off_images_per_sec"] > 0
    assert result["telemetry_on_images_per_sec"] > 0
    assert isinstance(result["telemetry_overhead_ok"], bool)
    assert (tmp_path / "tel_0.jsonl").exists()
