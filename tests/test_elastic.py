"""Elastic preemption-tolerant training (ISSUE 11, parallel/elastic.py).

Covers the pieces that don't need a multi-process jax cluster (which
jax 0.4.x cannot run on CPU — those paths are exercised by the host
backend, which IS multi-process at the gradient level):

* resharded restore — a checkpoint written at dp=4 restored onto a
  dp=2 virtual-device mesh, bit-faithful params and IDENTICAL next-step
  loss (the elastic-recovery correctness core);
* the checkpoint integrity guard — digests at save, corrupt restores
  refused with delete-or-use-previous guidance, verified fallback;
* ``initialize_multi_host`` retry/backoff + re-init (mocked
  jax.distributed — the real handshake needs a pod);
* the host-collective layer — slot-ordered TCP allreduce, fail-fast
  broken generations, and 2-worker collective training matching the
  plain single-process step;
* rendezvous protocol units (heartbeats, membership, argv rewriting,
  loss-trajectory files) and ``engine.train``'s resumable stop_check;
* an end-to-end subprocess run: 2 supervised workers, one SIGKILLed
  mid-epoch from outside, survivors re-form and finish — trajectory
  and final eval equal to an unkilled 1-worker reference of the same
  command (tools/elastic_bench.py drives the full kill+rejoin matrix;
  committed evidence in runs/elastic_r13/).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_vit_paper_replication_tpu import engine, parallel
from pytorch_vit_paper_replication_tpu.checkpoint import (
    CheckpointCorruptError, Checkpointer)
from pytorch_vit_paper_replication_tpu.configs import (MeshConfig,
                                                       TrainConfig,
                                                       ViTConfig)
from pytorch_vit_paper_replication_tpu.models import ViT
from pytorch_vit_paper_replication_tpu.optim import make_optimizer
from pytorch_vit_paper_replication_tpu.parallel import elastic
from pytorch_vit_paper_replication_tpu.parallel.elastic import (
    AllReduceServer, CollectiveFailure, ElasticWorkerContext,
    HostCollective, latest_checkpoint_step, make_host_collective_train_step,
    read_heartbeats, read_loss_trajectory, read_membership,
    rewrite_worker_paths, strip_elastic_args, write_heartbeat,
    write_membership)

REPO = Path(__file__).resolve().parent.parent


def _tiny_cfg():
    # All dropouts 0: the collective-equivalence tests compare across
    # batch layouts, and dropout noise is position-assigned.
    return ViTConfig(image_size=32, patch_size=8, num_layers=2,
                     num_heads=2, embedding_dim=32, mlp_size=64,
                     num_classes=3, dtype="float32",
                     attention_impl="xla", attn_dropout=0.0,
                     mlp_dropout=0.0, embedding_dropout=0.0)


def _make_state(cfg, ndev=1, devices=None):
    model = ViT(cfg)
    params = model.init(jax.random.key(1),
                        jnp.zeros((1, 32, 32, 3)))["params"]
    tx = make_optimizer(TrainConfig(batch_size=8), 100)
    state = engine.TrainState.create(apply_fn=model.apply, params=params,
                                     tx=tx, rng=jax.random.key(2))
    if devices is None and ndev == 1:
        return state, None
    mesh = parallel.make_mesh(MeshConfig(data=ndev),
                              devices=devices or jax.devices()[:ndev])
    return parallel.shard_train_state(state, mesh), mesh


def _batch(rng, n=8):
    return {"image": jnp.asarray(rng.normal(size=(n, 32, 32, 3)),
                                 jnp.float32),
            "label": jnp.asarray(rng.integers(0, 3, n), jnp.int32)}


# ------------------------------------------------------------------
# Resharded restore: the elastic correctness core.
# ------------------------------------------------------------------

def test_resharded_restore_dp4_to_dp2_bit_faithful(tmp_path, devices):
    """A dp=4-saved checkpoint loads onto a dp=2 mesh with bit-equal
    params/opt state and an IDENTICAL next-step loss — what survivor
    re-formation relies on."""
    cfg = _tiny_cfg()
    st4, mesh4 = _make_state(cfg, 4)
    rng = np.random.default_rng(0)
    batch = _batch(rng)
    # No donation: the two restores below may share buffers, and the
    # test steps both states.
    step = jax.jit(engine.make_train_step())
    st4, _ = step(st4, parallel.shard_batch(batch, mesh4))

    ck = Checkpointer(tmp_path / "ck")
    assert ck.save(st4, force=True)
    ck.wait()

    st2, mesh2 = _make_state(cfg, 2)
    st2 = ck.restore(st2)
    ref4, _ = _make_state(cfg, 4)
    ref4 = ck.restore(ref4)

    assert int(jax.device_get(st2.step)) == 1
    for a, b in zip(jax.tree.leaves(ref4.params),
                    jax.tree.leaves(st2.params)):
        np.testing.assert_array_equal(jax.device_get(a),
                                      jax.device_get(b))
    for a, b in zip(jax.tree.leaves(ref4.opt_state),
                    jax.tree.leaves(st2.opt_state)):
        np.testing.assert_array_equal(jax.device_get(a),
                                      jax.device_get(b))
    # The restored-on-dp2 leaves really live on the dp=2 mesh.
    leaf = jax.tree.leaves(st2.params)[0]
    assert leaf.sharding.mesh.shape["data"] == 2

    next_batch = _batch(rng)
    _, m2 = step(st2, parallel.shard_batch(next_batch, mesh2))
    _, m4 = step(ref4, parallel.shard_batch(next_batch, mesh4))
    assert float(jax.device_get(m2["loss_sum"])) == \
        float(jax.device_get(m4["loss_sum"]))
    assert float(jax.device_get(m2["grad_norm"])) == \
        float(jax.device_get(m4["grad_norm"]))
    ck.close()


# ------------------------------------------------------------------
# Checkpoint integrity guard.
# ------------------------------------------------------------------

def _save_steps(tmp_path, cfg, steps=(1, 2)):
    st, _ = _make_state(cfg)
    ck = Checkpointer(tmp_path / "ck", max_to_keep=4)
    for s in steps:
        ck.save(st.replace(step=jnp.asarray(s, jnp.int32)), force=True)
        ck.wait()
    return st, ck


def test_integrity_digest_recorded_and_verified(tmp_path):
    cfg = _tiny_cfg()
    st, ck = _save_steps(tmp_path, cfg)
    manifest = json.loads(ck.integrity_path.read_text())
    assert set(manifest["steps"]) == {"1", "2"}
    for rec in manifest["steps"].values():
        assert rec["files"] > 0 and rec["bytes"] > 0
        assert len(rec["sha256"]) == 64
    assert ck.verify(2) is True
    restored = ck.restore(st)  # verify=True default: clean restore
    assert int(jax.device_get(restored.step)) == 2
    ck.close()


def test_corrupt_restore_refused_with_guidance(tmp_path):
    cfg = _tiny_cfg()
    st, ck = _save_steps(tmp_path, cfg)
    # Flip one payload byte of the newest step: a torn write/bit rot.
    # Restrict to ocdbt data chunks (parent dir "d") — the largest file
    # overall is sometimes the _METADATA json, and corrupting THAT makes
    # the verify=False restore below fail on utf-8 decode instead of
    # exercising the opt-out path on damaged array bytes.
    victim = max((p for p in (tmp_path / "ck" / "2").rglob("*")
                  if p.is_file() and p.parent.name == "d"),
                 key=lambda p: p.stat().st_size)
    data = bytearray(victim.read_bytes())
    data[len(data) // 2] ^= 0xFF
    victim.write_bytes(bytes(data))

    with pytest.raises(CheckpointCorruptError) as err:
        ck.restore(st)
    msg = str(err.value)
    assert "Delete" in msg and "step=1" in msg  # use-previous guidance
    # verify=False opts out (forensics / I-know-what-I'm-doing).
    ck.restore(st, verify=False)
    # The elastic recovery path falls back to the previous good step.
    restored = ck.restore_latest_verified(st)
    assert int(jax.device_get(restored.step)) == 1

    # A DIGEST-LESS damaged newest step (the kill landed before its
    # digest finalized) surfaces as orbax's own error, not a digest
    # mismatch — recovery must still fall back, not churn.
    manifest = json.loads(ck.integrity_path.read_text())
    del manifest["steps"]["2"]
    ck.integrity_path.write_text(json.dumps(manifest))
    victim.write_bytes(b"")  # truncated payload file
    restored = ck.restore_latest_verified(st)
    assert int(jax.device_get(restored.step)) == 1
    ck.close()


def test_missing_digest_restores_unverified(tmp_path):
    """Pre-guard checkpoints (no digest recorded) restore with
    verify=True — the guard refuses corruption, not history."""
    cfg = _tiny_cfg()
    st, _ = _make_state(cfg)
    ck0 = Checkpointer(tmp_path / "ck", integrity=False)
    ck0.save(st.replace(step=jnp.asarray(3, jnp.int32)), force=True)
    ck0.close()
    ck = Checkpointer(tmp_path / "ck")
    assert ck.verify(3) is False  # no digest recorded -> unverifiable
    restored = ck.restore(st)
    assert int(jax.device_get(restored.step)) == 3
    ck.close()


def test_latest_checkpoint_step_scans_committed_only(tmp_path):
    d = tmp_path / "ck"
    (d / "100").mkdir(parents=True)
    (d / "100" / "_CHECKPOINT_METADATA").write_text("{}")
    (d / "200").mkdir()  # uncommitted (async save died mid-flight)
    (d / "integrity").mkdir()  # non-numeric clutter ignored
    assert latest_checkpoint_step(d) == 100
    assert latest_checkpoint_step(tmp_path / "absent") is None


# ------------------------------------------------------------------
# initialize_multi_host retry/backoff + re-init (mocked).
# ------------------------------------------------------------------

def test_initialize_multi_host_retries_with_backoff(monkeypatch):
    from pytorch_vit_paper_replication_tpu.telemetry import get_registry

    calls = {"init": 0, "sleep": []}

    def fake_init(**kwargs):
        calls["init"] += 1
        if calls["init"] < 3:
            raise RuntimeError("Barrier timed out connecting to "
                               "coordinator")

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    # mesh.py does `import time` at call time: patching the module
    # attribute reaches it.
    monkeypatch.setattr(time, "sleep",
                        lambda s: calls["sleep"].append(s))
    before = get_registry().snapshot()["counters"].get(
        "elastic_init_retries_total", 0)
    parallel.initialize_multi_host(
        coordinator_address="127.0.0.1:1", num_processes=2,
        process_id=0, retries=4, backoff_s=0.5)
    assert calls["init"] == 3
    assert calls["sleep"] == [0.5, 1.0]  # exponential
    after = get_registry().snapshot()["counters"].get(
        "elastic_init_retries_total", 0)
    assert after - before == 2


def test_initialize_multi_host_exhausted_raises(monkeypatch):
    def fake_init(**kwargs):
        raise RuntimeError("coordinator unreachable")

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setattr(time, "sleep", lambda s: None)
    with pytest.raises(RuntimeError, match="unreachable"):
        parallel.initialize_multi_host(retries=2, backoff_s=0.01)


def test_initialize_multi_host_reinitialize_calls_shutdown(monkeypatch):
    calls = []
    monkeypatch.setattr(jax.distributed, "shutdown",
                        lambda: calls.append("shutdown"))
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append("init"))
    parallel.initialize_multi_host(reinitialize=True)
    assert calls == ["shutdown", "init"]


# ------------------------------------------------------------------
# Host collective: TCP allreduce + fail-fast broken generations.
# ------------------------------------------------------------------

def test_allreduce_sums_slot_ordered():
    server = AllReduceServer()
    server.set_generation(0, 2)
    results = {}

    def member(slot, vec):
        c = HostCollective(server.address, slot=slot, generation=0,
                           timeout_s=20)
        results[slot] = [c.allreduce(np.asarray(v, np.float32))
                         for v in vec]
        c.close()

    t0 = threading.Thread(target=member, args=(0, [[1, 2], [3, 4]]))
    t1 = threading.Thread(target=member, args=(1, [[10, 20], [30, 40]]))
    t0.start(), t1.start()
    t0.join(10), t1.join(10)
    np.testing.assert_array_equal(results[0][0], [11, 22])
    np.testing.assert_array_equal(results[0][1], [33, 44])
    np.testing.assert_array_equal(results[0][0], results[1][0])
    server.close()


def test_allreduce_member_loss_fails_survivors_fast():
    """A member dying mid-step must break its generation: the blocked
    survivor gets CollectiveFailure immediately, not a socket timeout —
    the 'failed collective' loss-detection leg."""
    server = AllReduceServer()
    server.set_generation(0, 2)
    a = HostCollective(server.address, slot=0, generation=0, timeout_s=30)
    b = HostCollective(server.address, slot=1, generation=0, timeout_s=30)
    va = np.ones(4, np.float32)
    # One successful lockstep op first (allreduce blocks until every
    # member contributes, so the pair must run concurrently).
    got = {}
    tb = threading.Thread(
        target=lambda: got.setdefault("b", b.allreduce(va)))
    tb.start()
    out = a.allreduce(va)
    tb.join(10)
    np.testing.assert_array_equal(out, 2 * va)
    np.testing.assert_array_equal(got["b"], 2 * va)

    t0 = time.monotonic()
    errs = []

    def blocked():
        try:
            a.allreduce(va)
        except CollectiveFailure as e:
            errs.append(e)

    t = threading.Thread(target=blocked)
    t.start()
    time.sleep(0.2)
    b.close()  # SIGKILL-equivalent at the protocol level
    t.join(10)
    assert errs and time.monotonic() - t0 < 8
    # The generation stays broken for every subsequent op.
    with pytest.raises(CollectiveFailure):
        a.allreduce(va)
    a.close()
    server.close()


def test_host_collective_train_matches_single_process():
    """2 collective workers over interleaved batch shards == the plain
    single-process step over the full batch (same optimizer chain, same
    global gradient), and the workers' params stay replicated
    BIT-identically."""
    cfg = _tiny_cfg()
    rng = np.random.default_rng(3)
    batches = [_batch(rng, 8) for _ in range(3)]

    server = AllReduceServer()
    server.set_generation(0, 2)
    finals = {}
    losses = {0: [], 1: []}

    def worker(slot):
        st, _ = _make_state(cfg)
        coll = HostCollective(server.address, slot=slot, generation=0,
                              timeout_s=60)
        step = make_host_collective_train_step(
            st, collective=coll,
            on_step=lambda s, l, _slot=slot: losses[_slot].append(l))
        for full in batches:
            shard = {k: np.asarray(v)[slot::2] for k, v in full.items()}
            st, _m = step(st, {k: jnp.asarray(v)
                               for k, v in shard.items()})
        finals[slot] = jax.device_get(st.params)
        coll.close()

    threads = [threading.Thread(target=worker, args=(s,))
               for s in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    server.close()
    assert set(finals) == {0, 1}
    # Replicated state: BIT-equal across workers.
    for a, b in zip(jax.tree.leaves(finals[0]),
                    jax.tree.leaves(finals[1])):
        np.testing.assert_array_equal(a, b)
    assert losses[0] == losses[1]

    # And equal to the plain single-process trajectory up to summation
    # order (device-sums-8 vs host-sum of two device-sums-4).
    ref, _ = _make_state(cfg)
    ref_step = jax.jit(engine.make_train_step())
    ref_losses = []
    for full in batches:
        ref, m = ref_step(ref, full)
        m = jax.device_get(m)
        ref_losses.append(float(m["loss_sum"]) / float(m["count"]))
    np.testing.assert_allclose(losses[0], ref_losses, rtol=1e-5)
    # Params after 3 Adam steps agree only ABSOLUTELY: for coordinates
    # whose gradient is ~0, Adam's m/sqrt(v) is a SIGN function of the
    # last-ulp summation order, so each such coordinate may step ±lr
    # either way. The bound is a few lr (1e-3) units; a wrong global
    # gradient diverges far beyond it, and the loss-trajectory check
    # above pins the math tightly.
    for a, b in zip(jax.tree.leaves(finals[0]),
                    jax.tree.leaves(jax.device_get(ref.params))):
        np.testing.assert_allclose(a, b, atol=3e-3)


# ------------------------------------------------------------------
# Rendezvous protocol units.
# ------------------------------------------------------------------

def test_heartbeat_membership_roundtrip(tmp_path):
    write_heartbeat(tmp_path, 0, generation=2, step=17)
    write_heartbeat(tmp_path, 1, generation=2, step=16, pid=12345)
    beats = read_heartbeats(tmp_path)
    assert beats[0]["step"] == 17 and beats[0]["pid"] == os.getpid()
    assert beats[1]["pid"] == 12345
    (tmp_path / "heartbeat_9.json").write_text('{"torn')  # mid-write kill
    assert 9 not in read_heartbeats(tmp_path)

    assert read_membership(tmp_path) is None
    write_membership(tmp_path, generation=3, process_count=1,
                     reason="worker lost")
    m = read_membership(tmp_path)
    assert (m["generation"], m["process_count"]) == (3, 1)


def test_strip_and_rewrite_worker_argv():
    argv = ["--batch-size", "8", "--elastic", "2",
            "--elastic-rejoin-s", "5", "--elastic-backend=host",
            "--metrics-jsonl", "m.jsonl", "--seed", "1"]
    stripped = strip_elastic_args(argv)
    assert stripped == ["--batch-size", "8", "--metrics-jsonl",
                        "m.jsonl", "--seed", "1"]
    rewritten = rewrite_worker_paths(stripped, 1)
    # Slot tag goes BEFORE the extension: savefig/jsonl tooling infer
    # format from the suffix.
    assert "m.w1.jsonl" in rewritten
    assert rewrite_worker_paths(["--telemetry-jsonl=t.jsonl"], 0) == \
        ["--telemetry-jsonl=t.w0.jsonl"]
    assert rewrite_worker_paths(["--plot", "out/loss.png"], 2) == \
        ["--plot", os.path.join("out", "loss.w2.png")]
    assert rewrite_worker_paths(["--postmortem", "pm"], 1) == \
        ["--postmortem", "pm.w1"]


def test_read_loss_trajectory_last_wins(tmp_path):
    rows = [{"step": 1, "loss": 1.0}, {"step": 2, "loss": 0.9},
            {"step": 2, "loss": 0.8},  # redone after a restore
            {"step": 3, "loss": 0.7}]
    with open(tmp_path / elastic.LOSSES_NAME, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
        f.write('{"step": 4, "lo')  # torn tail: SIGKILL mid-write
    losses, redone = read_loss_trajectory(tmp_path)
    assert losses == {1: 1.0, 2: 0.8, 3: 0.7}
    assert redone == 1


def test_worker_context_stop_check_and_losses(tmp_path):
    ctx = ElasticWorkerContext(tmp_path, worker_id=0, process_count=1,
                               generation=0, heartbeat_s=0.05).start()
    try:
        assert ctx.process_info() == (0, 1)
        assert ctx.is_primary
        assert ctx.stop_check(5) is False
        ctx.record_loss(1, 0.5)
        ctx.record_loss(2, 0.4)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            hb = read_heartbeats(tmp_path).get(0)
            if hb and hb["step"] == 5:
                break
            time.sleep(0.05)
        assert read_heartbeats(tmp_path)[0]["step"] == 5
        # A newer membership generation requests a yield.
        write_membership(tmp_path, generation=1, process_count=2)
        deadline = time.monotonic() + 5
        while not ctx.stop_check(6) and time.monotonic() < deadline:
            time.sleep(0.05)
        assert ctx.stop_check(6) is True
        assert ctx.reform_pending
    finally:
        ctx.close()
    losses, _ = read_loss_trajectory(tmp_path)
    assert losses == {1: 0.5, 2: 0.4}


# ------------------------------------------------------------------
# Poisoned-compile-cache defenses (found by the fault-injection runs:
# a SIGKILL mid-cache-write left a truncated serialized executable,
# and every subsequent recovery segfaulted deserializing it).
# ------------------------------------------------------------------

def test_worker_cache_dir_parsing():
    from pytorch_vit_paper_replication_tpu.parallel.elastic import (
        worker_cache_dir)

    assert worker_cache_dir(["--compile-cache-dir", "/a"], {}) == \
        Path("/a")
    assert worker_cache_dir(["--compile-cache-dir=/b"], {}) == Path("/b")
    assert worker_cache_dir([], {"VIT_COMPILE_CACHE_DIR": "/c"}) == \
        Path("/c")
    assert worker_cache_dir([], {}) is None


def test_atomic_cache_put_never_leaves_torn_entry(tmp_path,
                                                  monkeypatch):
    """The hardened LRUCache.put writes temp + os.replace: a failure
    (or kill) anywhere before the rename leaves NO -cache file at the
    final path — a retried compile, never a segfaulting torn entry."""
    from pytorch_vit_paper_replication_tpu.compile_cache import (
        _install_atomic_cache_writes)

    _install_atomic_cache_writes()
    from jax._src.lru_cache import LRUCache

    cache = LRUCache(str(tmp_path / "c"), max_size=-1)
    cache.put("k1", b"payload-bytes")
    assert (tmp_path / "c" / "k1-cache").read_bytes() == b"payload-bytes"
    assert cache.get("k1") == b"payload-bytes"
    assert not list((tmp_path / "c").glob("*.tmp.*"))

    # Fail the atomic rename: final path must stay absent, temp cleaned.
    real_replace = os.replace

    def boom(src, dst):
        if "k2-cache" in str(dst):
            raise OSError("disk full")
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError):
        cache.put("k2", b"xx")
    monkeypatch.undo()
    assert not (tmp_path / "c" / "k2-cache").exists()
    assert not list((tmp_path / "c").glob("*.tmp.*"))


def test_supervisor_quarantines_stuck_cache(tmp_path):
    """Crash-loop breaker: consecutive worker-loss reforms pinned at
    the same restore step move the compile cache aside so the next
    generation recompiles instead of re-deserializing poison."""
    from pytorch_vit_paper_replication_tpu.parallel.elastic import (
        ElasticSupervisor)
    from pytorch_vit_paper_replication_tpu.telemetry import (
        TelemetryRegistry)

    cache = tmp_path / "cache"
    cache.mkdir()
    (cache / "entry").write_text("poison")
    reg = TelemetryRegistry()
    sup = ElasticSupervisor(
        ["--compile-cache-dir", str(cache)], num_workers=2,
        rendezvous=tmp_path / "rdv", checkpoint_dir=tmp_path / "ck",
        registry=reg, verbose=False)
    sup._maybe_quarantine_cache(500)   # progress resets...
    sup._maybe_quarantine_cache(700)
    sup._maybe_quarantine_cache(700)
    sup._maybe_quarantine_cache(700)
    assert cache.exists()              # threshold not hit yet
    sup._maybe_quarantine_cache(700)   # 3rd consecutive stuck loss
    assert not cache.exists()
    moved = list(tmp_path.glob("cache.quarantined.*"))
    assert len(moved) == 1 and (moved[0] / "entry").exists()
    assert reg.snapshot()["counters"][
        "elastic_cache_quarantines_total"] == 1


# ------------------------------------------------------------------
# engine.train stop_check: the resumable epoch boundary.
# ------------------------------------------------------------------

def test_engine_train_stop_check_yields_mid_epoch():
    cfg = _tiny_cfg()
    st, _ = _make_state(cfg)
    rng = np.random.default_rng(1)
    batches = [_batch(rng, 8) for _ in range(4)]
    seen = []

    def stop_check(step):
        seen.append(step)
        return step >= 2

    st, results = engine.train(
        st, lambda: iter(batches), lambda: iter(batches[:1]),
        epochs=3, verbose=False, stop_check=stop_check)
    # Stopped AT step 2, mid-epoch-1: no partial-epoch eval/log rows.
    assert int(jax.device_get(st.step)) == 2
    assert seen == [1, 2]
    assert results["train_loss"] == [] and results["test_loss"] == []


# ------------------------------------------------------------------
# End to end: SIGKILL a supervised worker mid-epoch, survivors finish,
# trajectory equals the unkilled reference.
# ------------------------------------------------------------------

def _spawn_supervisor(args, ckpt_dir, workers, extra=()):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers get their own device split
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + ([env["PYTHONPATH"]]
                       if env.get("PYTHONPATH") else []))
    cmd = [sys.executable, "-m",
           "pytorch_vit_paper_replication_tpu.train", *args,
           "--checkpoint-dir", str(ckpt_dir),
           "--elastic", str(workers), "--elastic-local-devices", "1",
           "--elastic-heartbeat-s", "0.3", "--elastic-timeout-s", "10",
           *extra]
    return subprocess.Popen(cmd, env=env, cwd=str(REPO),
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def test_elastic_e2e_kill_mid_epoch_matches_reference(tmp_path):
    """2 supervised workers; worker 1 is SIGKILLed from OUTSIDE (the
    harness reads its pid/step from the heartbeat file, like a
    preemption would give no warning); the survivor re-forms at pc=1,
    restores mid-epoch, finishes — and the whole per-step loss
    trajectory plus the final eval equal an unkilled 1-worker run of
    the same command."""
    from pytorch_vit_paper_replication_tpu.data import (
        make_synthetic_image_folder)

    train_dir, test_dir = make_synthetic_image_folder(
        tmp_path / "data", train_per_class=8, test_per_class=2,
        image_size=32)
    base = ["--train-dir", str(train_dir), "--test-dir", str(test_dir),
            "--image-size", "32", "--preset", "ViT-Ti/16",
            "--dtype", "float32", "--batch-size", "8", "--epochs", "2",
            "--seed", "42", "--dropout", "0", "--num-workers", "1",
            "--checkpoint-every-steps", "2",
            "--compile-cache-dir", str(tmp_path / "cache")]

    # Reference: same command, 1 worker, nobody dies. (Still the
    # host-collective path, so the loss recorder runs.)
    ref = _spawn_supervisor(base, tmp_path / "ck_ref", 1)
    out_ref, _ = ref.communicate(timeout=540)
    assert ref.returncode == 0, out_ref[-3000:]
    ref_losses, _ = read_loss_trajectory(tmp_path / "ck_ref" / "elastic")
    assert len(ref_losses) == 6  # 24 imgs / batch 8 * 2 epochs

    # Elastic: 2 workers, slot 1 killed once it reports step >= 4
    # (mid-epoch-2: the loader's mid-epoch skip math is in play).
    el_ckpt = tmp_path / "ck_el"
    rdv = el_ckpt / "elastic"
    proc = _spawn_supervisor(base, el_ckpt, 2)
    killed = {}

    def injector():
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline and not killed:
            hb = read_heartbeats(rdv).get(1) if rdv.is_dir() else None
            if hb and hb["step"] >= 4 and hb["generation"] == 0:
                try:
                    os.kill(int(hb["pid"]), signal.SIGKILL)
                    killed["pid"] = hb["pid"]
                except ProcessLookupError:
                    pass
                return
            time.sleep(0.1)

    t = threading.Thread(target=injector, daemon=True)
    t.start()
    out, _ = proc.communicate(timeout=540)
    t.join(5)
    assert proc.returncode == 0, out[-3000:]
    assert killed, "injector never fired (worker 1 never reached step 4)"

    summary = json.loads((rdv / "supervisor.json").read_text())
    assert summary["result"] == "completed"
    assert summary["recoveries"] == 1
    # Bounded redone work: the surviving primary checkpoints the
    # failure boundary, so at most the in-flight step is lost.
    assert summary["lost_steps_total"] <= 2

    el_losses, _redone = read_loss_trajectory(rdv)
    assert sorted(el_losses) == sorted(ref_losses)  # full coverage
    np.testing.assert_allclose(
        [el_losses[s] for s in sorted(el_losses)],
        [ref_losses[s] for s in sorted(ref_losses)], rtol=2e-5)
    ref_result = json.loads(
        (tmp_path / "ck_ref" / "elastic" / "result_0.json").read_text())
    el_result = json.loads((rdv / "result_0.json").read_text())
    np.testing.assert_allclose(
        el_result["results"]["test_loss"][-1],
        ref_result["results"]["test_loss"][-1], rtol=2e-5)
    assert el_result["final_step"] == ref_result["final_step"] == 6


@pytest.mark.slow
def test_restore_cache_hit_roundtrips_survive(tmp_path):
    """Regression for the recovery-path crash the fault-injection runs
    surfaced: on jax 0.4.x CPU, a DESERIALIZED persistent-cache
    executable with donated inputs heap-corrupts when run against
    orbax-restored arrays (SIGSEGV ~1 step after resume, every
    respawned generation). The host-collective apply jit is
    donation-free for exactly this reason — three consecutive
    save -> restore -> cache-HIT -> train round-trips must survive."""
    script = f"""
import jax, numpy as np, jax.numpy as jnp
from pytorch_vit_paper_replication_tpu import engine, parallel
from pytorch_vit_paper_replication_tpu.configs import (MeshConfig,
                                                       PRESETS,
                                                       TrainConfig)
from pytorch_vit_paper_replication_tpu.models import ViT
from pytorch_vit_paper_replication_tpu.optim import make_optimizer
from pytorch_vit_paper_replication_tpu.compile_cache import configure
from pytorch_vit_paper_replication_tpu.checkpoint import Checkpointer
from pytorch_vit_paper_replication_tpu.parallel.elastic import (
    make_host_collective_train_step)

configure({str(tmp_path / "cache")!r}, fingerprint="rt")
cfg = PRESETS["ViT-Ti/16"](num_classes=10, image_size=32,
                           dtype="float32", attn_dropout=0.0,
                           mlp_dropout=0.0, embedding_dropout=0.0)
model = ViT(cfg)
params = model.init(jax.random.key(42),
                    jnp.zeros((1, 32, 32, 3)))["params"]
tx = make_optimizer(TrainConfig(batch_size=16), 100)
state = engine.TrainState.create(apply_fn=model.apply, params=params,
                                 tx=tx,
                                 rng=jax.random.key(42,
                                                    impl="unsafe_rbg"))
mesh = parallel.make_mesh(MeshConfig(data=-1))
state = parallel.shard_train_state(state, mesh)
step = make_host_collective_train_step(state, collective=None)
ck = Checkpointer({str(tmp_path / "ck")!r})
if ck.latest_step() is not None:
    state = ck.restore_latest_verified(state)
rng = np.random.default_rng(0)
for _ in range(4):
    batch = {{"image": jnp.asarray(rng.normal(size=(16, 32, 32, 3)),
                                   jnp.float32),
              "label": jnp.asarray(rng.integers(0, 10, 16), jnp.int32)}}
    state, m = step(state, parallel.shard_batch(batch, mesh))
ck.save(state, force=True)
ck.wait()
ck.close()
print("ROUNDTRIP_OK", int(jax.device_get(state.step)))
"""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + ([env["PYTHONPATH"]]
                       if env.get("PYTHONPATH") else []))
    for i in range(3):
        out = subprocess.run([sys.executable, "-c", script], env=env,
                             cwd=str(REPO), capture_output=True,
                             text=True, timeout=540)
        assert out.returncode == 0, (
            f"round-trip {i} died (rc {out.returncode} — the "
            f"restore+cache-hit recovery path crashed):\n"
            f"{out.stdout[-2000:]}\n{out.stderr[-2000:]}")
        assert f"ROUNDTRIP_OK {(i + 1) * 4}" in out.stdout


@pytest.mark.slow
def test_elastic_bench_chaos_smoke(tmp_path):
    """The full harness in chaos mode (random kills) — slow tier:
    bench.py runs the deterministic-kill configuration every bench."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "elastic_bench", REPO / "tools" / "elastic_bench.py")
    eb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(eb)
    result = eb.run_elastic_bench(
        tmp_path / "out", records=1024, test_records=256, batch_size=16,
        epochs=2, image_size=32, checkpoint_every_steps=16,
        chaos=1, chaos_seed=3, rejoin_s=2.0, local_devices=1, workers=2,
        work_dir=tmp_path / "work")
    assert result["elastic_ok"], result
