"""Streaming windowed-shuffle pipeline (data/sampler.py + DataLoader
hooks): exactly-once visits, seeded determinism across worker types,
block-sequential degenerate case, shuffle quality, readahead hooks,
persistent process pool, and deterministic fork-worker seeding."""

import time

import numpy as np
import pytest

from pytorch_vit_paper_replication_tpu.data import (
    DataLoader,
    PackedShardDataset,
    create_packed_dataloaders,
    pack_image_folder,
    windowed_shuffle_order,
)
from pytorch_vit_paper_replication_tpu.data.imagenet import (
    ThreadLocalRng,
    eval_center_transform,
    train_augment_transform,
)
from pytorch_vit_paper_replication_tpu.data.sampler import BlockReadahead


@pytest.fixture(scope="module")
def packed_root(synthetic_folder, tmp_path_factory):
    train_dir, _ = synthetic_folder
    root = tmp_path_factory.mktemp("packed_ws")
    # Small shards so the 18-image set spans multiple blocks/shards.
    pack_image_folder(train_dir, root, pack_size=48, images_per_shard=8)
    return root


def _stream(n, block, block_order):
    return np.concatenate([
        np.arange(b * block, min((b + 1) * block, n), dtype=np.int64)
        for b in block_order])


# --- order properties -------------------------------------------------------


@pytest.mark.parametrize("n,window,block", [
    (100, 8, 16), (1000, 64, 32), (57, 1000, 10), (5, 2, 2), (1, 1, 1),
])
def test_windowed_order_is_permutation(n, window, block):
    """Every index exactly once per epoch, for windows smaller, larger,
    and equal to the dataset."""
    order, _ = windowed_shuffle_order(n, window, block,
                                      np.random.default_rng(0))
    assert sorted(order.tolist()) == list(range(n))


def test_windowed_order_deterministic():
    a, _ = windowed_shuffle_order(500, 64, 32, np.random.default_rng(7))
    b, _ = windowed_shuffle_order(500, 64, 32, np.random.default_rng(7))
    c, _ = windowed_shuffle_order(500, 64, 32, np.random.default_rng(8))
    assert np.array_equal(a, b)
    assert not np.array_equal(a, c)


def test_window_one_degenerates_to_block_sequential():
    """window=1 is the raw stream: shuffled blocks, each internally
    sequential — the pure-sequential-I/O end of the knob."""
    order, border = windowed_shuffle_order(100, 1, 16,
                                           np.random.default_rng(3))
    assert np.array_equal(order, _stream(100, 16, border))


def test_window_mixing_displacement():
    """The window demonstrably mixes: mean |emit - stream| position
    displacement >= window/4 (measures ~0.7x window empirically)."""
    n, w, bs = 20000, 2048, 512
    order, border = windowed_shuffle_order(n, w, bs,
                                           np.random.default_rng(0))
    stream = _stream(n, bs, border)
    stream_pos = np.empty(n, np.int64)
    stream_pos[stream] = np.arange(n)
    out_pos = np.empty(n, np.int64)
    out_pos[order] = np.arange(n)
    disp = np.abs(out_pos - stream_pos)
    assert disp.mean() >= w / 4
    # The property readahead relies on: nothing is emitted more than
    # `window` positions before it streams in.
    assert (stream_pos - out_pos).max() <= w


# --- loader integration -----------------------------------------------------


class _IdxDataset:
    """Labels are the index — makes visit sets directly observable."""

    classes = ["a"]

    def __init__(self, n=101):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        return np.zeros((2, 2, 3), np.float32), idx


def test_loader_windowed_exactly_once():
    dl = DataLoader(_IdxDataset(101), 8, shuffle=True, seed=0,
                    num_workers=1, shuffle_window=16, shuffle_block=8)
    seen = sorted(int(l) for b in dl for l in b["label"])
    assert seen == list(range(101))


def test_loader_windowed_sharded_partition_with_padding():
    """Multi-host shards of the windowed order partition the epoch
    exactly (same contract as the global shuffle), including the
    pad_shards path."""
    ds = _IdxDataset(101)

    def shard(pi):
        return DataLoader(ds, 8, shuffle=True, seed=5, process_index=pi,
                          process_count=2, pad_shards=True, num_workers=1,
                          shuffle_window=16, shuffle_block=8
                          )._local_indices(0)

    idx_a, valid_a = shard(0)
    idx_b, valid_b = shard(1)
    assert len(idx_a) == len(idx_b)  # equal step counts per host
    real_a = set(int(i) for i, v in zip(idx_a, valid_a) if v)
    real_b = set(int(i) for i, v in zip(idx_b, valid_b) if v)
    # Real (non-pad) rows are disjoint and cover everything.
    assert not (real_a & real_b)
    assert real_a | real_b == set(range(101))


def test_loader_windowed_visit_multiset_matches_global(packed_root):
    """Loader equality: the windowed path serves exactly the records the
    global-shuffle path serves (same multiset of labels and of decoded
    images), just in a different order."""
    ds = PackedShardDataset(packed_root,
                            eval_center_transform(32, normalize=False))
    def epoch(dl):
        labels, sums = [], []
        for b in dl:
            labels.extend(int(l) for l in b["label"])
            sums.extend(float(x.sum()) for x in b["image"])
        return sorted(labels), sorted(sums)
    g = epoch(DataLoader(ds, 4, shuffle=True, seed=3, num_workers=2))
    w = epoch(DataLoader(ds, 4, shuffle=True, seed=3, num_workers=2,
                         shuffle_window=6, shuffle_block=4))
    assert g[0] == w[0]
    np.testing.assert_allclose(g[1], w[1])


def test_loader_windowed_bit_reproducible_thread_vs_process(packed_root):
    """Acceptance: windowed epochs are bit-reproducible under --seed for
    both worker types (deterministic transform; the order is computed in
    the parent either way)."""
    ds = PackedShardDataset(packed_root,
                            eval_center_transform(32, normalize=False))
    kw = dict(shuffle=True, seed=5, num_workers=2, shuffle_window=6,
              shuffle_block=4)
    t1 = list(DataLoader(ds, 4, **kw))
    t2 = list(DataLoader(ds, 4, **kw))
    p = DataLoader(ds, 4, worker_type="process", **kw)
    p1 = list(p)
    p.close()
    assert len(t1) == len(p1) > 0
    for a, b, c in zip(t1, t2, p1):
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["image"], c["image"])
        np.testing.assert_array_equal(a["label"], c["label"])


def test_loader_windowed_mid_epoch_skip(packed_root):
    """skip_next_batches (mid-epoch resume) slices the windowed order
    exactly like the global one."""
    ds = PackedShardDataset(packed_root,
                            eval_center_transform(32, normalize=False))
    kw = dict(shuffle=True, seed=9, num_workers=1, shuffle_window=6,
              shuffle_block=4)
    full = list(DataLoader(ds, 4, **kw))
    resumed = DataLoader(ds, 4, **kw)
    resumed.skip_next_batches = 2
    got = list(resumed)
    assert len(got) == len(full) - 2
    for a, b in zip(full[2:], got):
        np.testing.assert_array_equal(a["image"], b["image"])


# --- readahead --------------------------------------------------------------


class _HookRecorder:
    """Wraps a dataset, recording willneed/evict hook calls."""

    def __init__(self, ds):
        self._ds = ds
        self.classes = ds.classes
        self.willneed = []
        self.evicted = []

    def __len__(self):
        return len(self._ds)

    def __getitem__(self, idx):
        return self._ds[idx]

    def willneed_records(self, lo, hi):
        self.willneed.append((lo, hi))
        self._ds.willneed_records(lo, hi)

    def evict_records(self, lo, hi):
        self.evicted.append((lo, hi))
        self._ds.evict_records(lo, hi)


def test_loader_readahead_hints_blocks(packed_root):
    ds = _HookRecorder(PackedShardDataset(
        packed_root, eval_center_transform(32, normalize=False)))
    dl = DataLoader(ds, 4, shuffle=True, seed=1, num_workers=2,
                    shuffle_window=6, shuffle_block=4, readahead=2,
                    evict_behind=True)
    batches = list(dl)
    assert len(batches) == 5  # 18 records / bs 4
    # Every block eventually hinted, ranges legal and block-aligned.
    covered = sorted(ds.willneed)
    assert {lo // 4 for lo, _ in covered} == set(range(5))  # 18/4 blocks
    for lo, hi in ds.willneed + ds.evicted:
        assert 0 <= lo < hi <= 18


class _HookCounter:
    def __init__(self):
        self.will, self.evict = [], []

    def willneed_records(self, lo, hi):
        self.will.append((lo, hi))

    def evict_records(self, lo, hi):
        self.evict.append((lo, hi))


def _poll(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline and not cond():
        time.sleep(0.005)


def test_block_readahead_controller_evicts_behind():
    """Controller check with a stepwise consumer: every block is hinted
    ahead of need, and drained blocks (minus the window-straggler
    margin) are evicted behind."""
    rec = _HookCounter()
    ra = BlockReadahead(rec, np.arange(8), 8, 64, depth=2, window=8,
                        evict_behind=True)
    # Initial hints before any consumption: needed(0) + depth blocks.
    _poll(lambda: len(rec.will) >= 4)
    for consumed in range(8, 65, 8):
        ra.advance(consumed)
        target = min(8, (consumed + 8) // 8 + 1 + 2)
        _poll(lambda: len(rec.will) >= target)
    _poll(lambda: len(rec.evict) >= 6)
    ra.close()
    assert len(rec.will) == 8
    # margin = window//block + 1 = 2 blocks kept resident
    assert len(rec.evict) == 6
    assert rec.evict == rec.will[:6]


def test_block_readahead_skips_resumed_prefix():
    """Mid-epoch resume: a consumer position far past the start must NOT
    page in the skipped prefix (the loader sliced those records off —
    they will never be read)."""
    rec = _HookCounter()
    ra = BlockReadahead(rec, np.arange(64), 8, 512, depth=2, window=8,
                        evict_behind=False)
    ra.advance(480)  # resume at 94%: only the tail blocks matter
    _poll(lambda: len(rec.will) >= 1, timeout=2.0)
    time.sleep(0.1)  # let any erroneous prefix walk show itself
    ra.close()
    # At most the pre-advance initial hints (4) + the live tail (~4
    # blocks): far below the 64-block full walk the old behavior did.
    assert 1 <= len(rec.will) <= 10


def test_readahead_inert_without_hooks_or_block_order():
    """Global-permutation order (no block structure) and hook-less
    datasets silently skip readahead."""
    dl = DataLoader(_IdxDataset(20), 4, shuffle=True, seed=0,
                    num_workers=1, readahead=2, shuffle_window=4)
    assert len(list(dl)) == 5  # hook-less dataset: runs fine
    dl2 = DataLoader(_IdxDataset(20), 4, shuffle=True, seed=0,
                     num_workers=1, readahead=2)  # global shuffle
    assert len(list(dl2)) == 5


# --- persistent pool + deterministic fork-worker seeding --------------------


class _PidDataset:
    classes = ["a"]

    def __len__(self):
        return 8

    def __getitem__(self, idx):
        import os

        return np.zeros((2, 2, 3), np.float32), os.getpid()


def test_process_pool_persists_across_epochs():
    """ADVICE r5 #2: one pool for the loader's lifetime — the same
    worker pids serve every epoch, and close() tears them down."""
    dl = DataLoader(_PidDataset(), 2, num_workers=1,
                    worker_type="process")
    pids1 = {int(l) for b in dl for l in b["label"]}
    pool = dl._pool
    assert pool is not None
    pids2 = {int(l) for b in dl for l in b["label"]}
    assert dl._pool is pool
    assert pids1 == pids2  # same forked workers, no epoch re-fork
    dl.close()
    assert dl._pool is None
    pids3 = {int(l) for b in dl for l in b["label"]}
    assert dl._pool is not pool  # re-forked after close
    assert pids3 != pids1
    dl.close()


def test_process_worker_augmentation_seeded_reproducible(packed_root):
    """ADVICE r5 #1 acceptance: --seed reproduces augmentation draws
    under worker_type='process' — two fresh single-worker loaders with
    the same seed yield bit-identical augmented epochs (workers seed
    from [seed, ordinal, pool_token], not os.urandom)."""
    def loader():
        ds = PackedShardDataset(packed_root, train_augment_transform(
            32, normalize=True, rng=ThreadLocalRng(7)))
        return DataLoader(ds, 4, shuffle=True, seed=7, num_workers=1,
                          worker_type="process", shuffle_window=6,
                          shuffle_block=4)

    l1, l2 = loader(), loader()
    e1, e2 = list(l1), list(l2)
    assert len(e1) == len(e2) > 0
    for a, b in zip(e1, e2):
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["label"], b["label"])
    # Augmentation stays LIVE across epochs (persistent pool: the worker
    # streams continue rather than replaying epoch 1).
    e1b = list(l1)
    assert not np.array_equal(e1[0]["image"], e1b[0]["image"])
    l1.close()
    l2.close()


def test_packed_dataset_page_hooks_are_noop_safe(packed_root):
    """The fadvise/madvise hooks never change what's read — only when."""
    ds = PackedShardDataset(packed_root)
    a5 = ds[5][0].copy()
    ds.willneed_records(0, len(ds))
    ds.evict_records(0, len(ds))
    np.testing.assert_array_equal(ds[5][0], a5)
    # ranges are clamped, odd inputs tolerated
    ds.willneed_records(-3, 10 ** 6)
    ds.evict_records(17, 17)


def test_pack_shuffle_seed_decorrelates_classes(synthetic_folder,
                                                tmp_path):
    """pack_image_folder(shuffle_seed=...) writes records class-mixed
    (the deep fix for windowed shuffling over class-major packs), keeps
    labels attached to their records, and is seed-deterministic."""
    from pytorch_vit_paper_replication_tpu.data import ImageFolderDataset

    train_dir, _ = synthetic_folder
    pack_image_folder(train_dir, tmp_path / "a", pack_size=16,
                      images_per_shard=8, shuffle_seed=3)
    pack_image_folder(train_dir, tmp_path / "b", pack_size=16,
                      images_per_shard=8, shuffle_seed=3)
    pack_image_folder(train_dir, tmp_path / "plain", pack_size=16,
                      images_per_shard=8)
    a = PackedShardDataset(tmp_path / "a")
    b = PackedShardDataset(tmp_path / "b")
    plain = PackedShardDataset(tmp_path / "plain")
    ref = ImageFolderDataset(train_dir)
    # Same multiset of labels, different order than class-major, same
    # order across same-seed packs.
    assert sorted(a.labels) == sorted(plain.labels)
    assert list(a.labels) == list(b.labels)
    assert list(a.labels) != list(plain.labels)
    assert list(plain.labels) == [s[1] for s in ref.samples]
    # Records follow their labels: every shuffled record matches the
    # class-major record carrying the same position in the permutation.
    order = np.random.default_rng(
        np.random.SeedSequence([3])).permutation(len(plain))
    for j in (0, 7, 17):
        np.testing.assert_array_equal(a[j][0], plain[int(order[j])][0])
        assert a[j][1] == plain[int(order[j])][1]


# --- scale harness ----------------------------------------------------------


def test_scale_epoch_harness_smoke(tmp_path):
    """tools/scale_epoch.py end-to-end at toy scale: synthetic pack is a
    valid PackedShardDataset, and the sustained protocol publishes its
    gate fields."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "scale_epoch", Path(__file__).resolve().parent.parent / "tools"
        / "scale_epoch.py")
    sc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sc)

    root = sc.make_synthetic_pack(tmp_path / "pack", records=96,
                                  pack_size=16, records_per_shard=32,
                                  seed=0)
    ds = PackedShardDataset(root)
    assert len(ds) == 96 and ds[95][0].shape == (16, 16, 3)
    res = sc.run_sustained(root, image_size=16, batch_size=8,
                           shuffle_window=32, readahead=1,
                           warm_records=32, num_workers=2,
                           compare_global=True, seed=0)
    assert res["records"] == 96
    assert set(res) >= {"sustained_epoch_ok", "sustained_vs_warm",
                        "warm_images_per_sec",
                        "sustained_images_per_sec", "cold_mode",
                        "global_shuffle_cold_images_per_sec"}


def test_train_cli_windowed_smoke(packed_root, synthetic_folder,
                                  tmp_path_factory):
    """--shuffle-window/--readahead wired through train.py end-to-end."""
    from pytorch_vit_paper_replication_tpu.train import main

    train_dir, test_dir = synthetic_folder
    root = tmp_path_factory.mktemp("packed_cli_ws")
    pack_image_folder(test_dir, root / "test", pack_size=48,
                      images_per_shard=8)
    results = main([
        "--dataset", "packed",
        "--train-dir", str(packed_root),
        "--test-dir", str(root / "test"),
        "--preset", "ViT-Ti/16", "--image-size", "32",
        "--patch-size", "16", "--dtype", "float32",
        "--epochs", "1", "--batch-size", "8", "--mesh-data", "8",
        "--shuffle-window", "8", "--readahead", "1",
    ])
    assert len(results["train_loss"]) == 1
    assert np.isfinite(results["train_loss"][0])
