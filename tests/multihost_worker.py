"""Shared logic + subprocess entry point for the 2-process CPU cluster test.

``run()`` holds the topology-independent training/eval recipe; the test
process calls it directly for the single-process reference, and ``main()``
(invoked as a subprocess per simulated host) wires it to a real
``jax.distributed`` 2-process cluster — 4 virtual CPU devices per process,
8 global — exercising the genuinely multi-process code paths that
single-process tests cannot: ``parallel.initialize_multi_host``, per-host
disjoint loader shards, and ``shard_batch``'s
``jax.make_array_from_process_local_data`` branch (VERDICT r2 #6: this was
dead code in every previous test and dryrun).

NOT a pytest module (no ``test_`` prefix): imported by
``test_multihost.py`` and executed as a script by its subprocesses.
"""

from __future__ import annotations

import json


def run(train_dir, test_dir, *, epochs: int = 2, global_batch: int = 16,
        checkpoint_dir=None, stop_after_steps=None, resume=False,
        mesh_model: int = 1) -> dict:
    """Train a tiny ViT on the 8-device 'data' mesh and eval exactly.

    Topology comes from the runtime: on a 2-process cluster each host
    loads its disjoint index shard and contributes its local quarter
    batches; single-process loads everything. Global math is identical
    up to fp32 reduction order.

    Checkpoint kwargs (VERDICT r3 #4 — the multi-PROCESS Orbax path):
    ``checkpoint_dir`` enables the managed :class:`Checkpointer` (shared
    filesystem, both processes call save/restore collectively);
    ``stop_after_steps`` saves at that step and returns early (simulated
    preemption — the caller kills nothing because the worker exits
    cleanly after an async-save wait, which is the durability contract);
    ``resume`` restores the latest checkpoint and continues with the
    loader's epoch/skip positioning, exactly train.py's resume math.
    ``mesh_model`` > 1 adds GSPMD tensor parallelism, so the
    checkpointed params/opt-state are MODEL-SHARDED arrays — the Orbax
    multi-process path for genuinely partitioned state, not just
    replicated leaves.
    """
    import jax
    import numpy as np

    if stop_after_steps is not None and checkpoint_dir is None:
        raise ValueError("stop_after_steps needs checkpoint_dir (the stop "
                         "point IS the checkpoint save)")

    from pytorch_vit_paper_replication_tpu import engine, parallel
    from pytorch_vit_paper_replication_tpu.configs import (MeshConfig,
                                                           TrainConfig,
                                                           ViTConfig)
    from pytorch_vit_paper_replication_tpu.data import (DataLoader,
                                                        ImageFolderDataset,
                                                        pad_batch)
    from pytorch_vit_paper_replication_tpu.data.transforms import (
        default_transform)
    from pytorch_vit_paper_replication_tpu.models import ViT
    from pytorch_vit_paper_replication_tpu.optim import make_optimizer

    pi, pc = parallel.process_info()
    cfg = ViTConfig(image_size=32, patch_size=8, num_layers=2, num_heads=2,
                    embedding_dim=32, mlp_size=64, num_classes=3,
                    dtype="float32", attention_impl="xla",
                    attn_dropout=0.0, mlp_dropout=0.0, embedding_dropout=0.0)
    assert global_batch % pc == 0
    tf = default_transform(cfg.image_size)
    train_dl = DataLoader(ImageFolderDataset(train_dir, tf),
                          global_batch // pc, shuffle=True, drop_last=True,
                          seed=5, num_workers=1,
                          process_index=pi, process_count=pc)
    test_dl = DataLoader(ImageFolderDataset(test_dir, tf),
                         global_batch // pc, shuffle=False, num_workers=1,
                         pad_shards=True, process_index=pi, process_count=pc)

    mesh = parallel.make_mesh(MeshConfig(data=-1, model=mesh_model))
    dp_size = mesh.shape["data"]
    steps_per_epoch = len(train_dl)
    model = ViT(cfg)
    params = model.init(
        jax.random.key(1),
        jax.numpy.zeros((1, cfg.image_size, cfg.image_size, 3)))["params"]
    tx = make_optimizer(TrainConfig(batch_size=global_batch),
                        steps_per_epoch * epochs)
    state = engine.TrainState.create(apply_fn=model.apply, params=params,
                                     tx=tx, rng=jax.random.key(2))
    state = parallel.shard_train_state(state, mesh)
    train_step = parallel.make_parallel_train_step(state, mesh)
    eval_step = parallel.make_parallel_eval_step(state, mesh)

    ckpt = None
    start_step = 0
    if checkpoint_dir is not None:
        from pytorch_vit_paper_replication_tpu.checkpoint import Checkpointer

        ckpt = Checkpointer(checkpoint_dir, max_to_keep=2)
        if resume:
            state = ckpt.restore(state)
            start_step = int(jax.device_get(state.step))
            # train.py's resume math: position the loader's shuffle epoch
            # and slice off the already-trained prefix at the index level.
            train_dl.epoch = start_step // steps_per_epoch
            train_dl.skip_next_batches = start_step % steps_per_epoch

    train_losses = []
    step_no = start_step
    stopped = False
    for _ in range(start_step // steps_per_epoch, epochs):
        for batch in train_dl:
            state, m = train_step(state, parallel.shard_batch(batch, mesh))
            m = jax.device_get(m)
            train_losses.append(float(m["loss_sum"]) / float(m["count"]))
            step_no += 1
            if stop_after_steps is not None and step_no >= stop_after_steps:
                # Simulated preemption point: collective save (both
                # processes participate — orbax's multi-process barrier +
                # primary-replica write), wait for durability, bail out.
                ckpt.save(state, force=True)
                ckpt.wait()
                stopped = True
                break
        if stopped:
            break

    import optax
    result = {
        "process_index": pi,
        "process_count": pc,
        "num_devices": jax.device_count(),
        "steps_per_epoch": steps_per_epoch,
        "final_step": int(jax.device_get(state.step)),
        "train_losses": train_losses,
        "stopped_early": stopped,
        "param_norm": float(
            jax.device_get(optax.global_norm(state.params))),
    }
    if stopped:
        # No eval on the preempted leg — the comparison happens after
        # resume completes the run.
        if ckpt is not None:
            ckpt.close()
        return result

    total = None
    for batch in test_dl:
        m = eval_step(state, parallel.shard_batch(
            pad_batch(batch, dp_size), mesh))
        m = jax.device_get(m)
        total = m if total is None else {
            k: total[k] + m[k] for k in total}
    result["eval_loss"] = float(total["loss_sum"]) / float(total["count"])
    result["eval_acc"] = float(total["correct"]) / float(total["count"])
    result["eval_count"] = float(total["count"])
    if ckpt is not None:
        ckpt.close()
    return result


def main() -> None:
    import argparse
    import os

    p = argparse.ArgumentParser()
    p.add_argument("--coordinator", required=True)
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--process-id", type=int, required=True)
    p.add_argument("--train-dir", required=True)
    p.add_argument("--test-dir", required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--stop-after", type=int, default=None)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--mesh-model", type=int, default=1)
    args = p.parse_args()

    # Must win over any ambient TPU/axon platform before jax initializes.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from pytorch_vit_paper_replication_tpu import parallel

    parallel.initialize_multi_host(coordinator_address=args.coordinator,
                                   num_processes=args.num_processes,
                                   process_id=args.process_id)
    assert jax.process_count() == args.num_processes, "cluster didn't form"
    result = run(args.train_dir, args.test_dir,
                 checkpoint_dir=args.checkpoint_dir,
                 stop_after_steps=args.stop_after, resume=args.resume,
                 mesh_model=args.mesh_model)
    with open(args.out, "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
