"""Integrated recipe-trajectory parity vs real torch (VERDICT r2 #5).

The optimizer pieces are individually torch-verified (test_optim.py,
test_transfer.py); this test closes the remaining gap: the FULL reference
training recipe — torch ``Adam(lr=1e-3, betas=(0.9,0.999))`` with coupled
L2 ``weight_decay=0.03`` on the ndim>1 param group (main nb cells 84-85),
``clip_grad_norm_(1.0)`` on raw grads (reference engine.py:63),
``SequentialLR(LinearLR(1e-6→1), LinearLR(1→0))`` stepped every optimizer
step (cells 87-88, engine.py:68), ``nn.CrossEntropyLoss`` — run for 50
steps from identical weights on identical batches, against our
``optim.make_optimizer`` + ``engine.make_train_step``. Loss and parameter
trajectories must agree to float32 accumulation tolerance, converting
"each piece is torch-verified" into "the recipe is equivalent" — the
strongest offline substitute for the reference's unreachable pretrained
accuracy gate (main nb cell 125: 0.9384).
"""

import copy

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")

from pytorch_vit_paper_replication_tpu import engine
from pytorch_vit_paper_replication_tpu.configs import TrainConfig
from pytorch_vit_paper_replication_tpu.models import ViT
from pytorch_vit_paper_replication_tpu.optim import make_optimizer
from pytorch_vit_paper_replication_tpu.transfer import (
    convert_torch_vit_state_dict,
)

from test_transfer import CFG, TorchMiniViT  # same-dir test module

N_STEPS = 50
BATCH = 8


def _batches():
    rng = np.random.default_rng(7)
    for _ in range(N_STEPS):
        x = rng.standard_normal(
            (BATCH, CFG.image_size, CFG.image_size, 3)).astype(np.float32)
        y = rng.integers(0, CFG.num_classes, BATCH).astype(np.int64)
        yield x, y


def _torch_trajectory(model):
    """The reference recipe verbatim: param groups (cell 84), Adam + wd
    (cell 85), warmup→decay SequentialLR (cells 87-88), clip-then-step
    (engine.py:63-68)."""
    decay, no_decay = [], []
    for name, p in model.named_parameters():
        (no_decay if p.ndim == 1 or name.endswith(".bias") else
         decay).append(p)
    opt = torch.optim.Adam(
        [{"params": decay, "weight_decay": 0.03},
         {"params": no_decay, "weight_decay": 0.0}],
        lr=1e-3, betas=(0.9, 0.999))
    warmup = int(0.05 * N_STEPS)
    sched = torch.optim.lr_scheduler.SequentialLR(
        opt,
        [torch.optim.lr_scheduler.LinearLR(
            opt, start_factor=1e-6, end_factor=1.0, total_iters=warmup),
         torch.optim.lr_scheduler.LinearLR(
             opt, start_factor=1.0, end_factor=0.0,
             total_iters=N_STEPS - warmup)],
        milestones=[warmup])
    loss_fn = torch.nn.CrossEntropyLoss()

    losses = []
    model.train()
    for x, y in _batches():
        xb = torch.from_numpy(x.transpose(0, 3, 1, 2))
        loss = loss_fn(model(xb), torch.from_numpy(y))
        opt.zero_grad()
        loss.backward()
        torch.nn.utils.clip_grad_norm_(model.parameters(), max_norm=1.0)
        opt.step()
        sched.step()
        losses.append(float(loss.detach()))
    return losses


def _jax_trajectory(initial_state_dict):
    params = convert_torch_vit_state_dict(initial_state_dict, CFG,
                                          include_head=True)
    tx = make_optimizer(
        TrainConfig(batch_size=BATCH, learning_rate=1e-3, weight_decay=0.03,
                    warmup_fraction=0.05, grad_clip_norm=1.0),
        N_STEPS)
    state = engine.TrainState.create(
        apply_fn=ViT(CFG).apply, params=jax.tree.map(jnp.asarray, params),
        tx=tx, rng=jax.random.key(0))  # dropout rates are all 0 in CFG
    step = jax.jit(engine.make_train_step(), donate_argnums=0)

    losses = []
    for x, y in _batches():
        batch = {"image": jnp.asarray(x),
                 "label": jnp.asarray(y.astype(np.int32))}
        state, metrics = step(state, batch)
        losses.append(float(jax.device_get(metrics["loss_sum"])) / BATCH)
    return losses, jax.device_get(state.params)


def test_recipe_trajectory_matches_torch():
    torch.manual_seed(3)
    model = TorchMiniViT(CFG)
    initial = copy.deepcopy(model.state_dict())

    torch_losses = _torch_trajectory(model)
    jax_losses, jax_params = _jax_trajectory(initial)

    # Per-step loss trajectory: fp32 forward parity is ~2e-4 relative
    # (test_forward_parity_with_torch); 50 steps of compounding stay well
    # inside 5e-3 when the recipes are the same — and diverge by >10x this
    # within a few steps if any piece (decay coupling, clip order,
    # schedule stepping) differs.
    np.testing.assert_allclose(jax_losses, torch_losses, rtol=5e-3,
                               atol=5e-3)

    # Final parameters: compare in our layout by converting the trained
    # torch weights, measuring each leaf's divergence RELATIVE to how far
    # training moved it (elementwise tolerances are meaningless for Adam:
    # near-zero-gradient coordinates get noise normalized up to full
    # lr-sized steps). One systematic recipe difference — decay coupling,
    # clip order, schedule off-by-one — moves leaves by O(1) of their
    # trajectory; fp32 chaos stays under a few percent.
    torch_final = convert_torch_vit_state_dict(model.state_dict(), CFG,
                                               include_head=True)
    torch_init = convert_torch_vit_state_dict(initial, CFG,
                                              include_head=True)
    flat_t = jax.tree_util.tree_leaves_with_path(torch_final)
    flat_0 = dict(jax.tree_util.tree_leaves_with_path(torch_init))
    flat_j = dict(jax.tree_util.tree_leaves_with_path(jax_params))
    assert len(flat_t) == len(flat_j)

    def rel_err(j, t, t0):
        move = np.linalg.norm(np.float64(t) - np.float64(t0))
        return np.linalg.norm(np.float64(j) - np.float64(t)) / max(move,
                                                                   1e-4)

    num = den = 0.0
    for path, leaf_t in flat_t:
        j = np.asarray(flat_j[path])
        t, t0 = np.asarray(leaf_t), np.asarray(flat_0[path])
        num += np.linalg.norm(np.float64(j) - np.float64(t)) ** 2
        den += np.linalg.norm(np.float64(t) - np.float64(t0)) ** 2
        key = jax.tree_util.keystr(path)
        if key.endswith("['qkv']['bias']"):
            # Attention projection biases live inside the softmax, where
            # their gradients are degenerate: the K bias has ANALYTICALLY
            # zero gradient (a constant added to every key shifts each
            # query's scores uniformly; softmax is shift-invariant — see
            # test_k_bias_gradient_vanishes), and the Q bias gradient is a
            # sum of cancelling score terms, so fp32 cancellation noise is
            # a large fraction of it. Adam then normalizes that noise into
            # lr-sized steps, making relative drift meaningless for this
            # leaf — bound its absolute drift instead (still ~1e-2, vs
            # O(weight-scale) if q/k/v were mis-mapped) and leave the
            # systematic check to the loss trajectory + global norm.
            assert np.abs(np.float64(j) - np.float64(t)).max() < 0.02, \
                f"{key} diverged beyond noise-drift bounds"
        else:
            assert rel_err(j, t, t0) < 0.05, f"param {key} diverged"
    assert (num ** 0.5) / (den ** 0.5) < 0.02, \
        "global parameter divergence exceeds fp32 accumulation noise"


def test_k_bias_gradient_vanishes():
    """The degeneracy the trajectory test exempts, proven directly: the
    loss gradient w.r.t. the key-projection bias vanishes (softmax shift
    invariance — what's left is fp32 rounding noise ~1e-4, vs O(0.1) for
    the q/v biases)."""
    model = ViT(CFG)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal(
        (4, CFG.image_size, CFG.image_size, 3)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, CFG.num_classes, 4).astype(np.int32))
    params = model.init(jax.random.key(0), x)["params"]

    def loss_fn(p):
        return engine.cross_entropy_loss(model.apply({"params": p}, x, False),
                                         y)

    grads = jax.grad(loss_fn)(params)
    for i in range(CFG.num_layers):
        g = np.asarray(
            grads["backbone"][f"encoder_block_{i}"]["msa"]["qkv"]["bias"])
        signal = max(np.abs(g[0]).max(), np.abs(g[2]).max())
        assert signal > 1e-3, "q/v bias gradients should carry signal"
        assert np.abs(g[1]).max() < 1e-2 * signal, \
            "k-bias grad should vanish up to fp32 rounding noise"
