"""Distributed tests on the virtual 8-device CPU mesh (SURVEY.md §4d):
data-parallel equivalence to single-device, tensor-parallel sharding rules,
ring-attention exactness, and the combined dp x tp train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_vit_paper_replication_tpu import engine, parallel
from pytorch_vit_paper_replication_tpu.configs import (
    MeshConfig, TrainConfig, ViTConfig)
from pytorch_vit_paper_replication_tpu.data import synthetic_batch
from pytorch_vit_paper_replication_tpu.models import ViT
from pytorch_vit_paper_replication_tpu.optim import make_optimizer

from conftest import requires_shard_map


def _make_state(cfg, total_steps=10, seed=0):
    model = ViT(cfg)
    rng = jax.random.key(seed)
    x = jnp.zeros((1, cfg.image_size, cfg.image_size, 3))
    params = model.init(rng, x)["params"]
    tx = make_optimizer(TrainConfig(warmup_fraction=0.1), total_steps)
    return engine.TrainState.create(
        apply_fn=model.apply, params=params, tx=tx, rng=rng)


def test_mesh_construction(devices):
    mesh = parallel.make_mesh(MeshConfig(data=4, model=2, seq=1))
    assert mesh.shape == {"data": 4, "model": 2, "seq": 1, "pipe": 1}
    mesh2 = parallel.make_mesh(MeshConfig(data=-1, model=2))
    assert mesh2.shape["data"] == 4


def test_mesh_bad_factorization(devices):
    with pytest.raises(ValueError):
        parallel.make_mesh(MeshConfig(data=3, model=2, seq=1))


def test_tp_rules_cover_vit_params(tiny_config):
    """Every encoder matmul is sharded; LN/embeddings/head replicated."""
    state_like = _make_state(tiny_config).params
    pspecs = parallel.tree_pspecs(state_like)
    blk = pspecs["backbone"]["encoder_block_0"]
    assert blk["msa"]["qkv"]["kernel"] == P(None, None, "model", None)
    assert blk["msa"]["out"]["kernel"] == P("model", None, None)
    assert blk["mlp"]["fc1"]["kernel"] == P(None, "model")
    assert blk["mlp"]["fc2"]["kernel"] == P("model", None)
    assert pspecs["backbone"]["encoder_norm"]["scale"] == P()
    assert pspecs["head"]["kernel"] == P()
    pe = pspecs["backbone"]["patch_embedding"]
    assert pe["pos_embedding"] == P()


def test_rules_apply_to_opt_state(tiny_config):
    """Adam mu/nu carry the same sub-paths, so TP rules shard them too —
    optimizer state memory scales down with the model axis."""
    state = _make_state(tiny_config)
    pspecs = parallel.tree_pspecs(state)
    # opt_state -> chain -> scale_by_adam state (mu) mirrors params paths.
    found = []
    jax.tree_util.tree_map_with_path(
        lambda path, leaf: found.append(
            parallel.pspec_for_path(path, leaf)) if any(
                getattr(k, "key", None) == "fc1" for k in path) else None,
        state.opt_state)
    assert any(spec == P(None, "model") for spec in found)


def test_validate_tp_divisibility(devices):
    mesh = parallel.make_mesh(MeshConfig(data=2, model=4))
    cfg = ViTConfig(image_size=32, patch_size=8, num_heads=2,
                    embedding_dim=32, mlp_size=64, num_layers=1,
                    dtype="float32")
    with pytest.raises(ValueError, match="num_heads"):
        parallel.validate_tp_divisibility(cfg, mesh)


def test_data_parallel_matches_single_device(tiny_config, devices):
    """DP over 8 devices computes the same loss/update as one device —
    gradient psum semantics equal the reference's full-batch step."""
    batch = jax.tree.map(jnp.asarray, synthetic_batch(
        16, tiny_config.image_size, tiny_config.num_classes))

    # Single-device baseline.
    state1 = _make_state(tiny_config)
    step1 = jax.jit(engine.make_train_step())
    state1, m1 = step1(state1, batch)

    # 8-way data parallel.
    mesh = parallel.make_mesh(MeshConfig(data=8))
    state8 = parallel.shard_train_state(_make_state(tiny_config), mesh)
    step8 = parallel.make_parallel_train_step(state8, mesh)
    state8, m8 = step8(state8, parallel.shard_batch(batch, mesh))

    np.testing.assert_allclose(
        float(m1["loss_sum"]), float(m8["loss_sum"]), rtol=1e-4)
    l1 = jax.tree.leaves(jax.device_get(state1.params))
    l8 = jax.tree.leaves(jax.device_get(state8.params))
    for a, b in zip(l1, l8):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5)


@requires_shard_map
def test_tensor_parallel_matches_single_device(tiny_config, devices):
    """dp=4 x tp=2: same numerics, params physically sharded over 'model'."""
    batch = jax.tree.map(jnp.asarray, synthetic_batch(
        8, tiny_config.image_size, tiny_config.num_classes))
    state1 = _make_state(tiny_config)
    step1 = jax.jit(engine.make_train_step())
    state1, m1 = step1(state1, batch)

    mesh = parallel.make_mesh(MeshConfig(data=4, model=2))
    parallel.validate_tp_divisibility(tiny_config, mesh)
    state_tp = parallel.shard_train_state(_make_state(tiny_config), mesh)
    # fc1 kernel really is sharded over the model axis.
    fc1 = state_tp.params["backbone"]["encoder_block_0"]["mlp"]["fc1"]["kernel"]
    assert fc1.sharding.spec == P(None, "model")

    step_tp = parallel.make_parallel_train_step(state_tp, mesh)
    state_tp, mtp = step_tp(state_tp, parallel.shard_batch(batch, mesh))
    np.testing.assert_allclose(
        float(m1["loss_sum"]), float(mtp["loss_sum"]), rtol=1e-4)
    a = jax.device_get(state1.params["backbone"]["encoder_block_0"]["mlp"]
                       ["fc1"]["kernel"])
    # Re-read from the post-step state (the pre-step array was donated).
    b = jax.device_get(state_tp.params["backbone"]["encoder_block_0"]["mlp"]
                       ["fc1"]["kernel"])
    np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5)


@requires_shard_map
def test_ring_attention_exact(devices):
    """Ring attention over the 'seq' axis equals full attention."""
    mesh = parallel.make_mesh(MeshConfig(data=1, model=1, seq=8))
    b, t, h, d = 2, 64, 2, 16   # t divisible by seq=8
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (b, t, h, d)) for kk in ks)
    ref = jax.nn.dot_product_attention(q, k, v)
    ring = parallel.make_ring_attention(mesh)
    out = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


@requires_shard_map
def test_ring_attention_with_dp(devices):
    """SP composes with DP on a 2x1x4 mesh."""
    mesh = parallel.make_mesh(MeshConfig(data=2, model=1, seq=4))
    b, t, h, d = 4, 32, 2, 16
    ks = jax.random.split(jax.random.key(1), 3)
    q, k, v = (jax.random.normal(kk, (b, t, h, d)) for kk in ks)
    ref = jax.nn.dot_product_attention(q, k, v)
    out = parallel.make_ring_attention(mesh)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_ragged_eval_batch_padded_dp(tiny_config, devices):
    """A ragged eval batch (11 examples on dp=8) must work via pad_batch +
    mask and produce example-exact metrics equal to single-device eval."""
    from pytorch_vit_paper_replication_tpu.data import pad_batch

    batch = jax.tree.map(jnp.asarray, synthetic_batch(
        11, tiny_config.image_size, tiny_config.num_classes))
    state1 = _make_state(tiny_config)
    m1 = jax.jit(engine.make_eval_step())(state1, batch)

    mesh = parallel.make_mesh(MeshConfig(data=8))
    state8 = parallel.shard_train_state(_make_state(tiny_config), mesh)
    padded = pad_batch(jax.tree.map(np.asarray, batch), 8)
    assert padded["label"].shape[0] == 16
    m8 = parallel.make_parallel_eval_step(state8, mesh)(
        state8, parallel.shard_batch(padded, mesh))
    assert float(m8["count"]) == 11.0
    np.testing.assert_allclose(float(m1["loss_sum"]),
                               float(m8["loss_sum"]), rtol=1e-4)
    np.testing.assert_allclose(float(m1["correct"]), float(m8["correct"]))


@requires_shard_map
def test_ring_attention_gradient(devices):
    """ppermute/scan are differentiable; the ring backward must equal the
    full-attention backward (VERDICT r1: ring had no gradient coverage)."""
    mesh = parallel.make_mesh(MeshConfig(data=1, model=1, seq=8))
    b, t, h, d = 2, 32, 2, 8
    ks = jax.random.split(jax.random.key(2), 3)
    q, k, v = (jax.random.normal(kk, (b, t, h, d)) for kk in ks)
    ring = parallel.make_ring_attention(mesh)

    def loss_ring(q, k, v):
        return jnp.sum(jnp.sin(ring(q, k, v)))

    def loss_full(q, k, v):
        return jnp.sum(jnp.sin(jax.nn.dot_product_attention(q, k, v)))

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=2e-2, atol=2e-4)


def _gap_config():
    """16 tokens (no CLS), divisible by seq-axis sizes 2/4/8."""
    return ViTConfig(image_size=32, patch_size=8, num_layers=2, num_heads=2,
                     embedding_dim=32, mlp_size=64, num_classes=3,
                     dtype="float32", attention_impl="xla", pool="gap")


def test_fused_mlp_train_step_on_dp_tp_mesh(tiny_config, devices):
    """PRODUCTION numerics multi-device (VERDICT r5 weak #4): the TPU
    default's fused Pallas MLP half-block (interpret mode on CPU —
    identical kernel code) + bf16 compute, jitted over the dp=4 x tp=2
    mesh. The reference for the loss is the SAME fused config on a
    single device: the mesh must not change the numerics (up to bf16
    reduction-order noise). Dropout is off for the equivalence: the
    fused kernel's positional-hash masks key on grid-LOCAL row indices,
    which differ between the sharded and single-device layouts (same
    statistics, different draws — the documented mask-stream caveat in
    ops/fused_mlp.py)."""
    fused_cfg = tiny_config.replace(mlp_impl="fused", dtype="bfloat16",
                                    mlp_dropout=0.0,
                                    embedding_dropout=0.0)
    batch = jax.tree.map(jnp.asarray, synthetic_batch(
        8, fused_cfg.image_size, fused_cfg.num_classes))

    state1 = _make_state(fused_cfg)
    step1 = jax.jit(engine.make_train_step())
    state1, m1 = step1(state1, batch)

    mesh = parallel.make_mesh(MeshConfig(data=4, model=2))
    parallel.validate_tp_divisibility(fused_cfg, mesh)
    state_f = parallel.shard_train_state(_make_state(fused_cfg), mesh)
    step_f = parallel.make_parallel_train_step(state_f, mesh)
    state_f, mf = step_f(state_f, parallel.shard_batch(batch, mesh))

    loss1 = float(m1["loss_sum"]) / float(m1["count"])
    loss_f = float(mf["loss_sum"]) / float(mf["count"])
    assert 0.0 < loss_f < 20.0, loss_f
    # bf16 compute: per-example losses are summed in different orders
    # under dp sharding, so the tolerance is bf16-scale, not f32-scale.
    np.testing.assert_allclose(loss1, loss_f, rtol=2e-2)
    # One optimizer step really applied on the sharded fused path.
    assert int(state_f.step) == 1


@requires_shard_map
def test_seq_parallel_train_step_matches_single_device(devices):
    """A full ViT train step on a data=2 x seq=4 mesh routes attention
    through the ring (ops.attention.sequence_parallel) and produces the
    same loss and parameter update as one device."""
    cfg = _gap_config()
    batch = jax.tree.map(jnp.asarray, synthetic_batch(
        8, cfg.image_size, cfg.num_classes))

    state1 = _make_state(cfg)
    step1 = jax.jit(engine.make_train_step())
    state1, m1 = step1(state1, batch)

    mesh = parallel.make_mesh(MeshConfig(data=2, model=1, seq=4))
    parallel.validate_mesh_for_config(cfg, mesh)
    state_sp = parallel.shard_train_state(_make_state(cfg), mesh)
    step_sp = parallel.make_parallel_train_step(state_sp, mesh)
    state_sp, msp = step_sp(state_sp, parallel.shard_batch(batch, mesh))

    np.testing.assert_allclose(
        float(m1["loss_sum"]), float(msp["loss_sum"]), rtol=1e-4)
    l1 = jax.tree.leaves(jax.device_get(state1.params))
    lsp = jax.tree.leaves(jax.device_get(state_sp.params))
    for a, b in zip(l1, lsp):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5)


@requires_shard_map
def test_seq_parallel_composes_with_tp(devices):
    """dp=2 x tp=2 x sp=2: heads shard over 'model' inside the ring
    shard_map, tokens over 'seq' — one step, same numerics."""
    cfg = _gap_config()
    batch = jax.tree.map(jnp.asarray, synthetic_batch(
        4, cfg.image_size, cfg.num_classes))
    state1 = _make_state(cfg)
    state1, m1 = jax.jit(engine.make_train_step())(state1, batch)

    mesh = parallel.make_mesh(MeshConfig(data=2, model=2, seq=2))
    parallel.validate_mesh_for_config(cfg, mesh)
    state3 = parallel.shard_train_state(_make_state(cfg), mesh)
    step3 = parallel.make_parallel_train_step(state3, mesh)
    state3, m3 = step3(state3, parallel.shard_batch(batch, mesh))
    np.testing.assert_allclose(
        float(m1["loss_sum"]), float(m3["loss_sum"]), rtol=1e-4)


@requires_shard_map
def test_seq_parallel_eval_step(devices):
    """Eval also routes through the ring and stays example-exact."""
    cfg = _gap_config()
    batch = jax.tree.map(jnp.asarray, synthetic_batch(
        8, cfg.image_size, cfg.num_classes))
    state1 = _make_state(cfg)
    m1 = jax.jit(engine.make_eval_step())(state1, batch)

    mesh = parallel.make_mesh(MeshConfig(data=2, model=1, seq=4))
    state_sp = parallel.shard_train_state(_make_state(cfg), mesh)
    msp = parallel.make_parallel_eval_step(state_sp, mesh)(
        state_sp, parallel.shard_batch(batch, mesh))
    np.testing.assert_allclose(
        float(m1["loss_sum"]), float(msp["loss_sum"]), rtol=1e-4)
    np.testing.assert_allclose(float(m1["correct"]), float(msp["correct"]))


def test_validate_sp_divisibility(devices):
    """CLS pool gives 17 tokens on 32/8 — indivisible by seq=4; the error
    must point at pool='gap'."""
    mesh = parallel.make_mesh(MeshConfig(data=2, model=1, seq=4))
    cfg = ViTConfig(image_size=32, patch_size=8, num_layers=1, num_heads=2,
                    embedding_dim=32, mlp_size=64, dtype="float32")
    with pytest.raises(ValueError, match="gap"):
        parallel.validate_sp_divisibility(cfg, mesh)
    parallel.validate_sp_divisibility(_gap_config(), mesh)  # 16 % 4 == 0


def test_grad_accum_composes_with_dp_tp_mesh(tiny_config, devices):
    """optax.MultiSteps adds a params-shaped grad accumulator to
    opt_state; the path-based sharding rules must cover it so accumulation
    works on a dp x tp mesh (effective-batch scaling on few chips)."""
    mesh = parallel.make_mesh(MeshConfig(data=4, model=2))
    model = ViT(tiny_config)
    rng = jax.random.key(0)
    x = jnp.zeros((1, tiny_config.image_size, tiny_config.image_size, 3))
    params = model.init(rng, x)["params"]
    tx = make_optimizer(TrainConfig(warmup_fraction=0.1), 5,
                        grad_accum_steps=2)
    state = engine.TrainState.create(
        apply_fn=model.apply, params=params, tx=tx, rng=rng)
    state = parallel.shard_train_state(state, mesh)
    step = parallel.make_parallel_train_step(state, mesh)
    batch = jax.tree.map(jnp.asarray, synthetic_batch(
        8, tiny_config.image_size, tiny_config.num_classes))

    p0 = jax.device_get(state.params)
    state, _ = step(state, parallel.shard_batch(batch, mesh))
    p1 = jax.device_get(state.params)
    assert all(np.array_equal(a, b) for a, b in zip(
        jax.tree.leaves(p0), jax.tree.leaves(p1)))   # micro-step 1: no update
    state, m = step(state, parallel.shard_batch(batch, mesh))
    p2 = jax.device_get(state.params)
    assert not all(np.array_equal(a, b) for a, b in zip(
        jax.tree.leaves(p1), jax.tree.leaves(p2)))   # micro-step 2: update
    assert np.isfinite(float(m["loss_sum"]))


# --- in-ring attention dropout (round 3) -----------------------------------


def _recover_ring_mask(mesh, b, h, t, rate, rng):
    """v=identity trick: with q=k=0 the ring's output rows ARE the dropped
    attention-weight rows (M * (1/t) / keep) — zero exactly where
    dropped."""
    z = jnp.zeros((b, t, h, t), jnp.float32)
    eye = jnp.broadcast_to(jnp.eye(t, dtype=jnp.float32)[None, :, None, :],
                           (b, t, h, t))
    ring = parallel.make_ring_attention(mesh, dropout_rate=rate,
                                        dropout_rng=rng,
                                        deterministic=False)
    weights = np.asarray(ring(z, z, eye)).transpose(0, 2, 1, 3)  # [B,H,T,T]
    return weights > 0.0, weights


@requires_shard_map
def test_ring_dropout_mask_statistics(devices):
    """In-ring dropout drops at the quantized rate with exact unbiased
    survivor rescale, and masks differ across (example, head)."""
    mesh = parallel.make_mesh(MeshConfig(data=2, model=1, seq=4))
    rate, b, h, t = 0.25, 2, 2, 128           # threshold 64, keep 0.75
    mask, weights = _recover_ring_mask(mesh, b, h, t, rate,
                                       jax.random.key(5))
    frac = 1.0 - mask.mean()
    assert abs(frac - 0.25) < 0.015, f"drop fraction {frac}"
    np.testing.assert_allclose(weights[mask], (1.0 / t) / 0.75, rtol=1e-5)
    assert (mask[0, 0] != mask[0, 1]).mean() > 0.1   # heads differ
    assert (mask[0, 0] != mask[1, 0]).mean() > 0.1   # examples differ


@requires_shard_map
def test_ring_dropout_matches_masked_reference_and_grads(devices):
    """EXACT fwd+bwd check: recover the ring's own mask (a pure function
    of (seed, example·head, global row/col) — independent of q/k/v), build
    the explicit masked-softmax reference, require outputs and all three
    gradients to agree. Also pins topology-invariance: the same seed on a
    different ring size must produce the same mask."""
    rate, b, t, h, d = 0.25, 2, 128, 2, 16
    rng = jax.random.key(7)
    mesh4 = parallel.make_mesh(MeshConfig(data=2, model=1, seq=4))
    mask, _ = _recover_ring_mask(mesh4, b, h, t, rate, rng)
    mask2, _ = _recover_ring_mask(
        parallel.make_mesh(MeshConfig(data=2, model=2, seq=2)),
        b, h, t, rate, rng)
    np.testing.assert_array_equal(mask, mask2)   # layout-independent
    mask = jnp.asarray(mask)

    ks = jax.random.split(jax.random.key(8), 3)
    q, k, v = (jax.random.normal(kk, (b, t, h, d)) for kk in ks)
    ring = parallel.make_ring_attention(mesh4, dropout_rate=rate,
                                        dropout_rng=rng,
                                        deterministic=False)

    def ring_loss(args):
        return (ring(*args).astype(jnp.float32) ** 2).sum()

    def ref_loss(args):
        q, k, v = args
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (d ** -0.5)
        p = jax.nn.softmax(s, axis=-1)
        z = jnp.where(mask, p, 0.0) / 0.75
        return (jnp.einsum("bhqk,bkhd->bqhd", z, v) ** 2).sum()

    np.testing.assert_allclose(ring_loss((q, k, v)), ref_loss((q, k, v)),
                               rtol=1e-4)
    g = jax.grad(ring_loss)((q, k, v))
    g_ref = jax.grad(ref_loss)((q, k, v))
    for name, a, r in zip("qkv", g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=2e-3,
                                   atol=2e-4, err_msg=f"d{name}")


@requires_shard_map
def test_sequence_parallel_dispatch_runs_dropout_in_ring(devices):
    """attn dropout no longer forces the sequence_parallel fallback: under
    the context the call must go through the ring (different rngs give
    different outputs; deterministic matches the no-dropout ring)."""
    from pytorch_vit_paper_replication_tpu.ops.attention import (
        dot_product_attention, sequence_parallel)

    mesh = parallel.make_mesh(MeshConfig(data=2, model=1, seq=4))
    b, t, h, d = 2, 32, 2, 16
    ks = jax.random.split(jax.random.key(2), 3)
    q, k, v = (jax.random.normal(kk, (b, t, h, d)) for kk in ks)
    with sequence_parallel(mesh):
        a1 = dot_product_attention(q, k, v, dropout_rate=0.3,
                                   dropout_rng=jax.random.key(1),
                                   deterministic=False)
        a2 = dot_product_attention(q, k, v, dropout_rate=0.3,
                                   dropout_rng=jax.random.key(2),
                                   deterministic=False)
        det = dot_product_attention(q, k, v, dropout_rate=0.3,
                                    deterministic=True)
    assert not np.allclose(np.asarray(a1), np.asarray(a2))
    ref = jax.nn.dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(det), np.asarray(ref), rtol=2e-2,
                               atol=2e-2)


@requires_shard_map
def test_ring_and_flash_dropout_masks_identical(devices):
    """The positional-hash mask is THE same function in both accelerated
    paths (ops.dropout.positional_keep_u8): for equal (seed, example·head,
    row, col) the flash kernel and the ring must drop the exact same
    attention weights."""
    from test_ops import _recover_drop_mask

    rate, b, h, t = 0.25, 2, 2, 128
    rng = jax.random.key(21)
    flash_mask, _ = _recover_drop_mask(rng, b, h, t, rate)   # [b*h, t, t]
    mesh = parallel.make_mesh(MeshConfig(data=2, model=1, seq=4))
    ring_mask, _ = _recover_ring_mask(mesh, b, h, t, rate, rng)  # [b,h,t,t]
    np.testing.assert_array_equal(ring_mask.reshape(b * h, t, t),
                                  flash_mask)
