"""ISSUE 19 — distill/ + the speculative two-tier serving cascade:
KD loss units, the distill-sink alignment refusals, the offline
``logits`` head pin, the calibrator's exact frontier, the router's
``model=`` hard filter, and :class:`CascadeRouter`'s escalation
semantics over a REAL mixed fleet of fake replicas (real sockets,
real dispatch/retry machinery — the replicas themselves are the
jax-free ``tests/data/fake_replica.py``, whose deterministic
``::probs`` rows let every branch of the cascade be pinned
byte-for-byte in tier-1 time)."""

import importlib.util
import json
import os
import socket
import sys
from pathlib import Path

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pytorch_vit_paper_replication_tpu.engine import (  # noqa: E402
    cross_entropy_loss, distill_loss)
from pytorch_vit_paper_replication_tpu.serve.cascade import (  # noqa: E402,E501
    CascadeRouter, EscalationDriftAlarm, load_cascade_config,
    softmax_margin)
from pytorch_vit_paper_replication_tpu.serve.offline import (  # noqa: E402
    OFFLINE_HEADS, NpySink, sink_sha256, write_progress)

REPO = Path(__file__).resolve().parent.parent
FAKE = REPO / "tests" / "data" / "fake_replica.py"
CLASSES = ["alpha", "beta", "gamma"]

_fake_spec = importlib.util.spec_from_file_location("fake_replica", FAKE)
fake_replica = importlib.util.module_from_spec(_fake_spec)
_fake_spec.loader.exec_module(fake_replica)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "tools" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ KD loss
def _np_log_softmax(x):
    x = x - x.max(axis=-1, keepdims=True)
    return x - np.log(np.exp(x).sum(axis=-1, keepdims=True))


def test_distill_loss_t1_matches_hand_computed_kl():
    rng = np.random.default_rng(0)
    s = rng.normal(size=(4, 5)).astype(np.float32)
    te = rng.normal(size=(4, 5)).astype(np.float32)
    y = np.array([0, 1, 2, 3])
    log_s, log_t = _np_log_softmax(s), _np_log_softmax(te)
    kl = (np.exp(log_t) * (log_t - log_s)).sum(-1).mean()
    got = float(distill_loss(jnp.asarray(s), jnp.asarray(te),
                             jnp.asarray(y), t=1.0, alpha=1.0))
    assert got == pytest.approx(float(kl), rel=1e-5)
    # KL vanishes when student == teacher (the distilled fixed point).
    same = float(distill_loss(jnp.asarray(s), jnp.asarray(s),
                              jnp.asarray(y), t=1.0, alpha=1.0))
    assert same == pytest.approx(0.0, abs=1e-6)


def test_distill_loss_alpha0_is_plain_ce():
    """alpha=0 degenerates BIT-EXACTLY to the ordinary objective — a
    distillation run with the knob at 0 is ordinary training (the
    static trace-time branch, not a numerical coincidence)."""
    rng = np.random.default_rng(1)
    s = jnp.asarray(rng.normal(size=(8, 3)).astype(np.float32))
    te = jnp.asarray(rng.normal(size=(8, 3)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 3, size=8))
    got = distill_loss(s, te, y, t=3.0, alpha=0.0)
    want = cross_entropy_loss(s, y, 0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_distill_loss_alpha_mixes_soft_and_hard():
    """The mix direction is pinned: alpha weights the SOFT term —
    loss(alpha) == (1-alpha)*CE + alpha*t^2*KL, exactly."""
    rng = np.random.default_rng(4)
    s = jnp.asarray(rng.normal(size=(6, 4)).astype(np.float32))
    te = jnp.asarray(rng.normal(size=(6, 4)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 4, size=6))
    hard = float(distill_loss(s, te, y, t=2.0, alpha=0.0))
    soft = float(distill_loss(s, te, y, t=2.0, alpha=1.0))
    mixed = float(distill_loss(s, te, y, t=2.0, alpha=0.3))
    assert mixed == pytest.approx(0.7 * hard + 0.3 * soft, rel=1e-5)


def test_distill_loss_t1_soft_gradient_matches_analytic():
    """At T=1 the pure-soft gradient wrt the student logits has the
    classic closed form ``(softmax(s) - softmax(t)) / B`` — the
    satellite contract pinning KD against the analytic derivation,
    not just against a re-implementation of the same code."""
    rng = np.random.default_rng(3)
    s = rng.normal(size=(4, 6)).astype(np.float32)
    te = rng.normal(size=(4, 6)).astype(np.float32)
    y = np.zeros(4, dtype=np.int64)
    grad = jax.grad(lambda sl: distill_loss(
        sl, jnp.asarray(te), jnp.asarray(y), t=1.0, alpha=1.0))(
        jnp.asarray(s))
    p_s = np.exp(_np_log_softmax(s))
    p_t = np.exp(_np_log_softmax(te))
    want = (p_s - p_t) / s.shape[0]
    np.testing.assert_allclose(np.asarray(grad), want,
                               rtol=1e-4, atol=1e-6)


def test_distill_loss_t_scaling_and_gradients():
    rng = np.random.default_rng(2)
    s = rng.normal(size=(4, 6)).astype(np.float32)
    te = rng.normal(size=(4, 6)).astype(np.float32)
    y = np.zeros(4, dtype=np.int64)
    # t^2 * KL(softened) — hand-computed at t=2.
    t = 2.0
    log_s, log_t = _np_log_softmax(s / t), _np_log_softmax(te / t)
    kl = (np.exp(log_t) * (log_t - log_s)).sum(-1).mean()
    got = float(distill_loss(jnp.asarray(s), jnp.asarray(te),
                             jnp.asarray(y), t=t, alpha=1.0))
    assert got == pytest.approx(t * t * float(kl), rel=1e-5)
    # The soft-target term must actually train the student.
    grad = jax.grad(lambda sl: distill_loss(
        sl, jnp.asarray(te), jnp.asarray(y), t=t, alpha=1.0))(
        jnp.asarray(s))
    assert float(jnp.abs(grad).sum()) > 0.0
    # ...and pull toward the teacher: one gradient step on the KD loss
    # must reduce it (sanity on sign/shape, not an optimizer test).
    stepped = jnp.asarray(s) - 0.5 * grad
    after = float(distill_loss(stepped, jnp.asarray(te),
                               jnp.asarray(y), t=t, alpha=1.0))
    assert after < got


def test_distill_train_step_two_steps_deterministic():
    """Two optimizer steps of the KD objective under fixed seeds are
    bit-deterministic (tier-1, CPU): rerunning from the same init and
    batches reproduces the params exactly, and the distill path
    reports the ``teacher_agree`` metric."""
    from pytorch_vit_paper_replication_tpu import configs, engine
    from pytorch_vit_paper_replication_tpu.models import ViT
    from pytorch_vit_paper_replication_tpu.optim import make_optimizer

    cfg = configs.ViTConfig(
        num_classes=3, image_size=16, patch_size=8, num_layers=2,
        num_heads=2, embedding_dim=16, mlp_size=32, dtype="float32")

    def batches():
        rng = np.random.default_rng(7)
        out = []
        for _ in range(2):
            out.append({
                "image": jnp.asarray(rng.normal(
                    size=(4, 16, 16, 3)).astype(np.float32)),
                "label": jnp.asarray(
                    rng.integers(0, 3, size=4).astype(np.int32)),
                "teacher_logits": jnp.asarray(rng.normal(
                    size=(4, 3)).astype(np.float32) * 3.0)})
        return out

    def run():
        model = ViT(cfg)
        rng = jax.random.key(0)
        params = model.init(rng, jnp.zeros((1, 16, 16, 3)))["params"]
        tx = make_optimizer(configs.TrainConfig(), 2)
        state = engine.TrainState.create(
            apply_fn=model.apply, params=params, tx=tx, rng=rng)
        step = jax.jit(engine.make_train_step(
            distill_alpha=0.7, distill_t=2.0), donate_argnums=0)
        metrics = None
        for batch in batches():
            state, metrics = step(state, batch)
        return state.params, metrics

    p1, m1 = run()
    p2, m2 = run()
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), p1, p2)
    assert "teacher_agree" in m1
    assert 0 <= float(m1["teacher_agree"]) <= 4


# ------------------------------------------- distill-sink alignment
def _write_sink(out_dir, rows, *, head="logits", seal=True,
                records_done=None, **overrides):
    """A batch_infer-shaped sink dir from an in-memory matrix."""
    out_dir.mkdir(parents=True, exist_ok=True)
    n, c = rows.shape
    sink = NpySink(out_dir / "outputs.npy", rows=n, dim=c, resume=False)
    sink.write(0, rows.astype(np.float32))
    sink.flush()
    sink.close()
    payload = {"fingerprint": "fp-test", "head": head,
               "total_records": n, "out_dim": c, "batch_size": n,
               "ladder": [n], "sink": "outputs.npy",
               "records_done": n if records_done is None
               else records_done,
               "rows_written": n if records_done is None
               else records_done}
    if seal:
        payload["sink_sha256"] = sink_sha256(out_dir / "outputs.npy")
    payload.update(overrides)
    write_progress(out_dir, payload)
    return out_dir


def test_load_distill_sink_happy_path_and_refusals(tmp_path):
    from pytorch_vit_paper_replication_tpu.train import load_distill_sink

    rows = np.random.default_rng(3).normal(size=(16, 3)).astype(
        np.float32)
    good = _write_sink(tmp_path / "good", rows)
    got, manifest = load_distill_sink(good, n_records=16, n_classes=3)
    np.testing.assert_array_equal(np.asarray(got), rows)
    assert manifest["head"] == "logits"

    # Wrong pack: sink record count != this run's train split.
    with pytest.raises(SystemExit, match="wrong pack"):
        load_distill_sink(good, n_records=17, n_classes=3)
    # Wrong label space.
    with pytest.raises(SystemExit, match="label space"):
        load_distill_sink(good, n_records=16, n_classes=4)
    # Wrong head: probs rows cannot be temperature-softened.
    probs_sink = _write_sink(tmp_path / "probs", rows, head="probs")
    with pytest.raises(SystemExit, match="--head logits"):
        load_distill_sink(probs_sink, n_records=16, n_classes=3)
    # Unfinished dump.
    part = _write_sink(tmp_path / "part", rows, records_done=8,
                       seal=False)
    with pytest.raises(SystemExit, match="incomplete"):
        load_distill_sink(part, n_records=16, n_classes=3)
    # Never sealed (no sink_sha256).
    unsealed = _write_sink(tmp_path / "unsealed", rows, seal=False)
    with pytest.raises(SystemExit, match="sink_sha256"):
        load_distill_sink(unsealed, n_records=16, n_classes=3)
    # Modified after sealing: sha mismatch refuses.
    torn = _write_sink(tmp_path / "torn", rows)
    m = np.lib.format.open_memmap(torn / "outputs.npy", mode="r+")
    m[3, 1] += 1.0
    m.flush()
    del m
    with pytest.raises(SystemExit, match="sha256 mismatch"):
        load_distill_sink(torn, n_records=16, n_classes=3)
    # No manifest at all.
    with pytest.raises(SystemExit, match="progress.json"):
        load_distill_sink(tmp_path / "empty", n_records=16,
                          n_classes=3)


# ----------------------------------------------------- offline heads
def test_offline_heads_registry_is_the_single_source():
    """serve/offline.py's head registry IS what batch_infer --head
    offers — a head added to one place reaches both consumers."""
    assert set(OFFLINE_HEADS) >= {"probs", "features", "logits"}
    src = (REPO / "tools" / "batch_infer.py").read_text()
    assert "sorted(OFFLINE_HEADS)" in src


@pytest.mark.slow
def test_logits_head_is_presoftmax_slice_of_probs_program(tmp_path):
    """The ISSUE 19 contract pin: the ``logits`` head's rows are the
    pre-softmax float32 values of the SAME forward the ``probs`` head
    serves — softmax(logits rows) reproduces the probs rows to
    float32 roundoff and argmax EXACTLY, so distilling from logits
    and serving probs are two views of one program."""
    from pytorch_vit_paper_replication_tpu.configs import PRESETS
    from pytorch_vit_paper_replication_tpu.models import ViT
    from pytorch_vit_paper_replication_tpu.serve.offline import (
        OfflineEngine)

    cfg = PRESETS["ViT-Ti/16"](num_classes=3, image_size=32,
                               patch_size=16, dtype="float32")
    model = ViT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 32, 32, 3)))["params"]
    rng = np.random.default_rng(4)
    data = [(rng.random((32, 32, 3)).astype(np.float32), 0)
            for _ in range(8)]
    out = {}
    for head in ("logits", "probs"):
        eng = OfflineEngine(model, params, head=head, image_size=32,
                            buckets=(8,), class_names=CLASSES)
        eng.run(data, tmp_path / head, batch_size=8)
        out[head] = np.array(np.lib.format.open_memmap(
            tmp_path / head / "outputs.npy", mode="r"))
    resoft = np.asarray(jax.nn.softmax(jnp.asarray(out["logits"]),
                                       axis=-1))
    np.testing.assert_allclose(resoft, out["probs"], atol=1e-6)
    np.testing.assert_array_equal(resoft.argmax(1),
                                  out["probs"].argmax(1))


# ------------------------------------------------- cascade semantics
def test_softmax_margin():
    assert softmax_margin(np.array([0.7, 0.2, 0.1])) == \
        pytest.approx(0.5)
    assert softmax_margin(np.array([0.5, 0.5])) == pytest.approx(0.0)
    assert softmax_margin(np.array([1.0])) == 1.0   # degenerate 1-class


def _cascade_fleet(tmp_path, threshold, *,
                   models=("student", "teacher"), **router_kw):
    """A mixed student/teacher fleet of fake replicas under a
    :class:`CascadeRouter`. ``--probs-by-path`` keys every replica's
    ``::probs`` row on the requested path too, so each image carries
    its own margin and a mid threshold genuinely splits traffic."""
    from pytorch_vit_paper_replication_tpu.serve.fleet import (
        ReplicaManager, ReplicaSpec)
    from pytorch_vit_paper_replication_tpu.telemetry.registry import (
        TelemetryRegistry)

    registry = TelemetryRegistry()
    specs = [ReplicaSpec(rid=f"r{i}", checkpoint=str(tmp_path / f"ck{i}"),
                         model=m)
             for i, m in enumerate(models)]
    manager = ReplicaManager(
        specs,
        command_factory=lambda spec: [sys.executable, str(FAKE),
                                      "--ckpt", spec.checkpoint,
                                      "--probs-by-path"],
        env_factory=lambda spec: dict(os.environ),
        health_interval_s=0.05, stale_after_s=2.0,
        registry=registry)
    router = CascadeRouter(manager, registry=registry,
                           request_timeout_s=30.0,
                           threshold=threshold, **router_kw)
    return manager, router


def test_cascade_threshold_zero_is_student_only(tmp_path):
    """threshold=0: the inclusive ``margin <= 0`` gate escalates only
    exact top-1/top-2 ties, and no fake-replica softmax row ties
    exactly — the cascade IS the student fleet and the teacher
    replica's completed counter stays at zero."""
    manager, router = _cascade_fleet(tmp_path, 0.0)
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        paths = [f"img{i}.jpg" for i in range(4)]
        replies = _ask(router.address,
                       [f"::probs {p}" for p in paths] + [paths[0]])
        ck0 = str(tmp_path / "ck0")
        for p, reply in zip(paths, replies[:4]):
            row = fake_replica.probs_for_path(ck0, p)
            assert json.loads(reply) == {
                "label": "fake", "prob": max(row), "probs": row}
        # The TSV classifier path formats the student's row into the
        # serve CLI's exact ``path\\tlabel\\tprob`` reply shape.
        row0 = fake_replica.probs_for_path(ck0, paths[0])
        assert replies[4] == f"{paths[0]}\tfake\t{max(row0):.4f}"
        s1 = json.loads(manager.request("r1", "::stats"))
        assert s1["counters"]["completed"] == 0   # teacher NEVER touched
        c = router.counters()
        assert c["requests"] == 5 and c["escalated"] == 0
        assert c["served_student"] == 5 and c["served_teacher"] == 0


def test_cascade_threshold_inf_is_teacher_only_bit_identical(tmp_path):
    """threshold=inf: every row escalates, and each reply is
    BIT-IDENTICAL to asking the teacher replica directly — the
    escalation relays the unmodified ``::probs`` line and returns the
    teacher's bytes untouched."""
    manager, router = _cascade_fleet(tmp_path, float("inf"))
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        paths = [f"img{i}.jpg" for i in range(4)]
        replies = _ask(router.address, [f"::probs {p}" for p in paths])
        # Count BEFORE the direct comparison requests below add to it.
        s0 = json.loads(manager.request("r0", "::stats"))
        s1 = json.loads(manager.request("r1", "::stats"))
        for p, reply in zip(paths, replies):
            assert reply == manager.request("r1", f"::probs {p}")
        # Exactly-once: the student row was speculated then CONSUMED
        # by the router — each tier saw each request exactly once and
        # the client got exactly one reply per line.
        assert s0["counters"]["completed"] == 4
        assert s1["counters"]["completed"] == 4
        c = router.counters()
        assert c["requests"] == c["escalated"] == 4
        assert c["served_teacher"] == 4 and c["served_student"] == 0
        assert c["escalation_rate"] == 1.0
        snap = router.snapshot()["cascade"]
        assert snap["threshold"] == float("inf")
        assert snap["student_model"] == "student"
        assert snap["teacher_model"] == "teacher"


def test_cascade_mid_threshold_splits_by_margin_exactly_once(tmp_path):
    """The load-bearing case: each image's own student margin decides
    its tier — low-margin rows come back as the teacher's bytes, the
    rest as the student's — and the per-replica completed counters
    prove exactly-once accounting on both legs."""
    paths = [f"img{i:02d}.jpg" for i in range(12)]
    ck0 = str(tmp_path / "ck0")
    margins = {p: softmax_margin(fake_replica.probs_for_path(ck0, p))
               for p in paths}
    ranked = sorted(margins.values())
    thr = (ranked[5] + ranked[6]) / 2.0          # median split
    assert ranked[5] < thr <= ranked[6]          # non-degenerate
    low = [p for p in paths if margins[p] <= thr]
    manager, router = _cascade_fleet(tmp_path, thr)
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        replies = dict(zip(paths, _ask(
            router.address, [f"::probs {p}" for p in paths])))
        s0 = json.loads(manager.request("r0", "::stats"))
        s1 = json.loads(manager.request("r1", "::stats"))
        for p in paths:
            rid = "r1" if p in low else "r0"
            assert replies[p] == manager.request(rid, f"::probs {p}")
        assert s0["counters"]["completed"] == len(paths)  # all speculated
        assert s1["counters"]["completed"] == len(low)    # escalations only
        c = router.counters()
        assert c["escalated"] == c["served_teacher"] == len(low) > 0
        assert c["served_student"] == len(paths) - len(low) > 0


def test_cascade_margin_exactly_at_threshold_escalates(tmp_path):
    """ISSUE 19 pins the boundary: the gate is the INCLUSIVE
    ``margin <= threshold``, so a row whose margin lands EXACTLY on
    the threshold is a teacher answer — by contract, not by float
    luck or implementation choice. The fake replica's probs row
    round-trips JSON exactly, so setting the threshold to the row's
    own margin constructs the equality case deterministically."""
    ck0 = str(tmp_path / "ck0")
    path = "img00.jpg"
    thr = softmax_margin(fake_replica.probs_for_path(ck0, path))
    assert 0.0 < thr < 1.0
    manager, router = _cascade_fleet(tmp_path, thr)
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        (reply,) = _ask(router.address, [f"::probs {path}"])
        # Escalated: the client got the TEACHER's bytes.
        assert reply == manager.request("r1", f"::probs {path}")
        c = router.counters()
        assert c["escalated"] == 1 and c["served_teacher"] == 1
        assert c["served_student"] == 0


def test_cascade_scopes_to_default_slice_only(tmp_path):
    """An explicit ``model=`` pin or a non-default head is direct
    tier access — it rides the plain router path and never
    speculates, even at threshold=inf."""
    manager, router = _cascade_fleet(tmp_path, float("inf"))
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        replies = _ask(router.address, [
            "::model student", "img.jpg", "::model -",
            "::req head=features img2.jpg",
        ])
        assert replies[0] == "::model\tok\tstudent"
        # Pinned straight at the student despite threshold=inf.
        assert replies[1].split("\t")[1] == \
            "ck0:probs:interactive:student"
        assert replies[2] == "::model\tok\t-"
        assert replies[3].split("\t")[1].endswith(
            ":features:interactive")
        assert router.counters()["requests"] == 0   # never speculated


def test_cascade_failover_and_fallback_are_loud_not_silent(tmp_path):
    """No routable student → unconditional teacher failover
    (availability beats economy); a failed escalation → the student's
    valid low-margin row (a degraded answer beats an error). Both
    paths count instead of hiding."""
    # Student tier absent entirely: every request fails over.
    manager, router = _cascade_fleet(tmp_path, 0.0, models=("teacher",))
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        (reply,) = _ask(router.address, ["::probs img.jpg"])
        assert reply == manager.request("r0", "::probs img.jpg")
        c = router.counters()
        assert c["student_failover"] == 1 and c["served_teacher"] == 1
    # Teacher tier absent: the escalation fails, the student row ships.
    manager, router = _cascade_fleet(tmp_path, float("inf"),
                                     models=("student",))
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        (reply,) = _ask(router.address, ["::probs img.jpg"])
        assert reply == manager.request("r0", "::probs img.jpg")
        c = router.counters()
        assert c["escalated"] == c["teacher_fallback"] == 1
        assert c["served_student"] == 1


def test_load_cascade_config_refusals_and_precedence(tmp_path):
    cfg = tmp_path / "cascade.json"
    with pytest.raises(SystemExit, match="cascade config"):
        load_cascade_config(cfg)                     # missing file
    cfg.write_text("not json")
    with pytest.raises(SystemExit, match="not valid JSON"):
        load_cascade_config(cfg)
    cfg.write_text("{}")
    with pytest.raises(SystemExit, match="threshold"):
        load_cascade_config(cfg)
    cfg.write_text('{"threshold": -0.5}')
    with pytest.raises(SystemExit, match=">= 0"):
        load_cascade_config(cfg)
    cfg.write_text(json.dumps({
        "threshold": 0.2, "applied_threshold": 0.15,
        "predicted_agreement": 0.99,
        "predicted_escalation_rate": 0.08}))
    out = load_cascade_config(cfg)
    # The calibrator's floor-adjusted pick wins over the raw knee.
    assert out["threshold"] == 0.15
    assert out["predicted_agreement"] == 0.99
    assert out["predicted_escalation_rate"] == 0.08


def test_cascade_router_validates_and_boots_from_config(tmp_path):
    from pytorch_vit_paper_replication_tpu.serve.fleet import (
        ReplicaManager, ReplicaSpec)

    manager = ReplicaManager(
        [ReplicaSpec(rid="r0", checkpoint=str(tmp_path / "ck0"),
                     model="student")],
        command_factory=lambda spec: [sys.executable, str(FAKE),
                                      "--ckpt", spec.checkpoint])
    with pytest.raises(ValueError, match=">= 0"):
        CascadeRouter(manager, threshold=-0.5)
    with pytest.raises(ValueError, match=">= 0"):
        CascadeRouter(manager, threshold=float("nan"))
    with pytest.raises(ValueError, match="share the model tag"):
        CascadeRouter(manager, threshold=0.1, student_model="m",
                      teacher_model="m")
    cfg = tmp_path / "cascade.json"
    cfg.write_text(json.dumps({"threshold": 0.3,
                               "predicted_agreement": 0.97}))
    with CascadeRouter.from_config(manager, cfg) as router:
        assert router.threshold == 0.3
        assert router.predicted_agreement == 0.97


# ------------------------------------------------------- tuner math
def test_tune_threshold_exact_frontier():
    ct = _load_tool("calibrate_cascade")
    rng = np.random.default_rng(8)
    margins = rng.uniform(0, 1, 500)
    agree = rng.uniform(0, 1, 500) < (0.55 + 0.45 * margins)
    out = ct.tune_threshold(margins, agree, target_agreement=0.99)
    # Applying the chosen threshold reproduces the prediction exactly:
    # the frontier is computed, not estimated.
    esc = margins <= out["threshold"]
    assert esc.mean() == pytest.approx(out["predicted_escalation_rate"])
    applied = (esc | agree).mean()
    assert applied == pytest.approx(out["predicted_agreement"],
                                    abs=1e-6)
    assert applied >= 0.99
    # The curve is a monotone frontier in escalation rate.
    rates = [p["escalation_rate"] for p in out["curve"]]
    agrees = [p["agreement"] for p in out["curve"]]
    assert rates == sorted(rates) and agrees == sorted(agrees)
    # Endpoints: all-agree needs no escalation; perfect fidelity over
    # all-disagree needs all of it.
    assert ct.tune_threshold(margins, np.ones(500, bool),
                             target_agreement=0.99)["threshold"] == 0.0
    full = ct.tune_threshold(margins, np.zeros(500, bool),
                             target_agreement=1.0)
    assert full["predicted_escalation_rate"] == 1.0
    assert (margins <= full["threshold"]).all()
    # The harness floor escalates at least the asked-for share.
    thr = ct.threshold_for_escalation(margins, 0.25)
    assert (margins <= thr).mean() >= 0.25


def test_tune_threshold_lands_on_tie_and_includes_it():
    ct = _load_tool("calibrate_cascade")
    margins = np.array([0.1, 0.1, 0.1, 0.5, 0.9])
    agree = np.array([False, True, True, True, True])
    out = ct.tune_threshold(margins, agree, target_agreement=0.95)
    # The disagreeing row shares its margin with two agreeing rows:
    # no cut can split the tie group, so the calibrator places the
    # threshold EXACTLY on the tied margin and the serve-side
    # inclusive ``margin <= threshold`` gate escalates all three.
    assert out["threshold"] == pytest.approx(0.1)
    assert (margins <= out["threshold"]).sum() == 3
    assert out["predicted_agreement"] == 1.0


def test_margins_from_sinks_and_refusals(tmp_path):
    ct = _load_tool("calibrate_cascade")
    s = np.array([[2.0, 1.0, 0.0], [0.0, 3.0, 0.0], [1.0, 1.0, 5.0]],
                 np.float32)
    t = np.array([[0.9, 0.05, 0.05], [0.1, 0.1, 0.8], [0.1, 0.1, 0.8]],
                 np.float32)
    s_dir = _write_sink(tmp_path / "student", s, head="logits")
    t_dir = _write_sink(tmp_path / "teacher", t, head="probs")
    margins, agree = ct.margins_from_sinks(s_dir, t_dir)
    # Student softmax margins, hand-computed from the logit rows.
    p = np.exp(s - s.max(1, keepdims=True))
    p /= p.sum(1, keepdims=True)
    want = np.sort(p, axis=1)
    np.testing.assert_allclose(margins, want[:, -1] - want[:, -2],
                               rtol=1e-6)
    assert list(agree) == [True, False, True]
    # Mismatched splits refuse.
    short = _write_sink(tmp_path / "short", s[:2], head="logits")
    with pytest.raises(SystemExit, match="SAME pack"):
        ct.margins_from_sinks(short, t_dir)
    # Shadow JSONL round-trips the same math.
    jl = tmp_path / "shadow.jsonl"
    jl.write_text("".join(
        json.dumps({"margin": float(m), "agree": bool(a),
                    "shift": 0.0}) + "\n"
        for m, a in zip(margins, agree)))
    m2, a2 = ct.margins_from_jsonl(jl)
    np.testing.assert_allclose(m2, margins, rtol=1e-6)
    np.testing.assert_array_equal(a2, agree)


# ----------------------------------------- router model= hard filter
def _fake_fleet(tmp_path, models):
    from pytorch_vit_paper_replication_tpu.serve.fleet import (
        FleetRouter, ReplicaManager, ReplicaSpec)
    from pytorch_vit_paper_replication_tpu.telemetry.registry import (
        TelemetryRegistry)

    registry = TelemetryRegistry()
    specs = [ReplicaSpec(rid=f"r{i}", checkpoint=str(tmp_path / f"ck{i}"),
                         model=m)
             for i, m in enumerate(models)]
    manager = ReplicaManager(
        specs,
        command_factory=lambda spec: [sys.executable, str(FAKE),
                                      "--ckpt", spec.checkpoint],
        env_factory=lambda spec: dict(os.environ),
        health_interval_s=0.05, stale_after_s=2.0,
        registry=registry)
    router = FleetRouter(manager, registry=registry,
                         request_timeout_s=30.0)
    return manager, router


def _ask(address, lines, timeout=30.0):
    with socket.create_connection(address, timeout=timeout) as sock:
        sock.settimeout(timeout)
        rfile = sock.makefile("r", encoding="utf-8")
        replies = []
        for line in lines:
            sock.sendall((line + "\n").encode())
            replies.append(rfile.readline().rstrip("\n"))
        rfile.close()
        return replies


def test_router_model_filter_steers_and_echoes(tmp_path):
    """ISSUE 19: ``::model M`` / inline ``model=M`` HARD-filter
    routing to replicas whose spec declares that tier — the fake's
    tag echo proves which model tag was relayed, and the per-replica
    completed counters prove which replica served it."""
    manager, router = _fake_fleet(tmp_path, ["student", "teacher"])
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        replies = _ask(router.address, [
            "::model teacher", "img1.jpg",
            "::model -", "img2.jpg",
            "::req model=student img3.jpg",
        ])
        assert replies[0] == "::model\tok\tteacher"
        path, tag, _prob = replies[1].split("\t")
        # Relayed inline as model=teacher and served by r1 (ck1).
        assert path == "img1.jpg"
        assert tag == "ck1:probs:interactive:teacher"
        assert replies[2] == "::model\tok\t-"
        # Cleared: back to the bare-path relay, any replica.
        assert replies[3].split("\t")[0] == "img2.jpg"
        assert ":" not in replies[3].split("\t")[1]
        # One-shot inline override pins the student replica (ck0).
        path, tag, _prob = replies[4].split("\t")
        assert path == "img3.jpg"
        assert tag == "ck0:probs:interactive:student"
        s0 = json.loads(manager.request("r0", "::stats"))
        s1 = json.loads(manager.request("r1", "::stats"))
        # img1 pinned to r1, img3 pinned to r0 by the filter; img2 was
        # unfiltered and may land on either replica.
        assert s0["counters"]["completed"] >= 1   # img3
        assert s1["counters"]["completed"] >= 1   # img1
        assert s0["counters"]["completed"] + \
            s1["counters"]["completed"] == 3


def test_router_unknown_model_is_explicit_backpressure(tmp_path):
    """A model name no replica declares must answer an explicit
    error (hard filter — NEVER a silent fallback to the wrong tier)."""
    manager, router = _fake_fleet(tmp_path, ["student", "student"])
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        (reply,) = _ask(router.address,
                        ["::req model=teacher img.jpg"])
        assert "\tERROR\t" in reply
        # ...and the filtered tier still works on the same fleet.
        (ok_reply,) = _ask(router.address,
                           ["::req model=student img.jpg"])
        assert ok_reply.split("\t")[1] == "ck0:probs:interactive:student" \
            or ok_reply.split("\t")[1] == "ck1:probs:interactive:student"


def test_policy_model_filter_is_hard_and_precedes_affinity():
    from pytorch_vit_paper_replication_tpu.serve.fleet import (
        LeastLoadedAffinity, ReplicaView)

    def view(rid, model, warm=(1,), inflight=0):
        return ReplicaView(rid=rid, address=("127.0.0.1", 1), up=True,
                           draining=False, inflight=inflight,
                           queue_depth=0, warm_rungs=warm, restarts=0,
                           model=model)

    pol = LeastLoadedAffinity()
    views = [view("r0", "student", warm=(8,), inflight=0),
             view("r1", "teacher", warm=(1,), inflight=9)]
    # Hard filter beats both load AND rung affinity: r0 is idle and
    # warm for the rung, but it is the wrong tier.
    assert pol.choose(views, rung=8, model="teacher") == "r1"
    assert pol.choose(views, model="nope") is None
    assert pol.choose(views) == "r0"   # no model asked: filter off


def test_build_serve_command_emits_model_tier():
    """A spec's declared tier rides into the replica's argv as
    ``--model-tier`` (so the replica's own ::stats self-reports its
    deployment ROLE); an untiered spec emits no flag at all."""
    from pytorch_vit_paper_replication_tpu.serve.fleet import (
        ReplicaSpec, build_serve_command)

    tiered = build_serve_command(
        ReplicaSpec(rid="r0", checkpoint="/ck", model="student"),
        classes_file="/classes.txt")
    i = tiered.index("--model-tier")
    assert tiered[i + 1] == "student"
    plain = build_serve_command(
        ReplicaSpec(rid="r1", checkpoint="/ck"),
        classes_file="/classes.txt")
    assert "--model-tier" not in plain


# --------------------------------------------------- drift alarm (r20)
def test_drift_alarm_silent_on_calibration_distribution():
    """ISSUE 20: fed the distribution the threshold was calibrated ON
    (a deterministic stream whose rate IS the prediction), the alarm
    never fires and never goes active — no matter how long it runs."""
    from pytorch_vit_paper_replication_tpu.telemetry.registry import (
        TelemetryRegistry)

    reg = TelemetryRegistry()
    alarm = EscalationDriftAlarm(0.25, band=0.10, window=64,
                                 min_samples=16, registry=reg)
    # 1-in-4 escalates: window rate sits exactly on expected_rate.
    for i in range(512):
        assert alarm.observe(i % 4 == 0) is False
    snap = alarm.snapshot()
    assert snap["active"] is False and snap["fired"] == 0
    assert abs(snap["window_rate"] - 0.25) < 0.05
    counters = reg.snapshot()["counters"]
    assert counters.get("cascade_drift_alarms_total", 0) == 0
    assert not [e for e in reg.last_events(50)
                if e["event"] == "cascade_escalation_drift"]


def test_drift_alarm_fires_once_on_shift_with_hysteresis():
    """A synthetic distribution shift (escalate-everything after a
    calibrated warmup) fires the alarm EXACTLY ONCE — hysteresis holds
    it active across the whole excursion — and the registry ring event
    carries the ``refit_cmd`` hint the operator needs. Returning in
    band re-arms it: a second excursion fires a second time."""
    from pytorch_vit_paper_replication_tpu.telemetry.registry import (
        TelemetryRegistry)

    reg = TelemetryRegistry()
    alarm = EscalationDriftAlarm(
        0.25, band=0.10, window=32, min_samples=32, registry=reg,
        refit_cmd="python tools/calibrate_cascade.py --json-out c.json")
    for i in range(32):                      # calibrated warmup
        assert alarm.observe(i % 4 == 0) is False
    fired_at = [alarm.observe(True) for _ in range(64)]
    assert sum(fired_at) == 1                # one band exit, one firing
    assert fired_at.index(True) < 8          # fired early in the shift
    assert alarm.active and alarm.fired == 1
    assert alarm.window_rate() == 1.0
    (ev,) = [e for e in reg.last_events(100)
             if e["event"] == "cascade_escalation_drift"]
    assert ev["refit_cmd"].startswith("python tools/calibrate_cascade")
    assert ev["expected_rate"] == 0.25 and ev["band"] == 0.10
    assert ev["window_rate"] > 0.35
    # Recovery: back in band re-arms; a fresh excursion fires again.
    for i in range(64):
        assert alarm.observe(i % 4 == 0) is False
    assert not alarm.active
    assert any(alarm.observe(True) for _ in range(64))
    assert alarm.fired == 2
    g = reg.snapshot()["gauges"]
    assert g["cascade_drift_alarm_active"] == 1.0
    assert reg.snapshot()["counters"]["cascade_drift_alarms_total"] == 2


def test_drift_alarm_min_samples_gates_and_ctor_refuses():
    """Too few observations is NOT evidence: a full-escalation burst
    shorter than ``min_samples`` stays silent. Nonsense calibrations
    (rate outside [0,1], non-positive band) are refused loudly."""
    from pytorch_vit_paper_replication_tpu.telemetry.registry import (
        TelemetryRegistry)

    alarm = EscalationDriftAlarm(0.1, band=0.05, window=128,
                                 min_samples=50,
                                 registry=TelemetryRegistry())
    assert not any(alarm.observe(True) for _ in range(49))
    assert alarm.observe(True)               # 50th observation arms it
    with pytest.raises(ValueError, match="expected_rate"):
        EscalationDriftAlarm(1.5, registry=TelemetryRegistry())
    with pytest.raises(ValueError, match="band"):
        EscalationDriftAlarm(0.5, band=0.0, registry=TelemetryRegistry())


def test_cascade_router_wires_drift_alarm_end_to_end(tmp_path):
    """A live fleet: ``predicted_escalation_rate`` arms the alarm on
    the router, real margin-gated decisions feed it, and a threshold
    that escalates EVERYTHING against a near-zero prediction drifts it
    out of band — visible in ``snapshot()["cascade"]["drift"]`` and
    the registry ring."""
    manager, router = _cascade_fleet(
        tmp_path, float("inf"),               # every row escalates
        predicted_escalation_rate=0.05, drift_band=0.10,
        drift_window=8, drift_min_samples=4,
        refit_cmd="python tools/calibrate_cascade.py")
    assert router.drift_alarm is not None
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        paths = [f"img{i:02d}.jpg" for i in range(6)]
        _ask(router.address, [f"::probs {p}" for p in paths])
        drift = router.snapshot()["cascade"]["drift"]
        assert drift["window_rate"] == 1.0
        assert drift["active"] is True and drift["fired"] == 1
        assert drift["expected_rate"] == 0.05
        events = [e for e in router._registry.last_events(50)
                  if e["event"] == "cascade_escalation_drift"]
        assert len(events) == 1
        assert "calibrate_cascade" in events[0]["refit_cmd"]
    # Unarmed router (no prediction) has no alarm and a None snapshot.
    manager2, router2 = _cascade_fleet(tmp_path, 0.5)
    assert router2.drift_alarm is None
    assert router2.snapshot()["cascade"]["drift"] is None


# --------------------------------------------------- bench wiring
def test_cascade_gate_rides_the_compact_line():
    src = (REPO / "bench.py").read_text()
    assert '"cascade_ok"' in src
    assert '"cascade_speedup"' in src and '"cascade_agreement"' in src
    spec = importlib.util.spec_from_file_location(
        "bench_mod_casc", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert "cascade_speedup" in bench.COMPACT_EXTRA_KEYS
    assert "cascade_agreement" in bench.COMPACT_EXTRA_KEYS
