"""Contract tests for low-precision attention-probs storage (r6).

The bytes-side attack (ops/quant.py + ops/attention.py's
_quantized_softmax_pv): the materialized softmax weights — and/or the
backward residual — stored in 8-bit formats. Pinned contracts:

* pack/unpack round-trip error per format stays within the bounds
  ops/quant.py publishes (a broken scale or rounding mode fails loudly);
* ``attention_probs_dtype="bf16"`` is BIT-identical to the pre-r6 path,
  outputs and grads (it routes through the same code, not a lookalike);
* a degenerate fully-masked row yields the exact-zero output on every
  storage format (the saturating-softmax zero-row semantics survive
  quantization: quantize(0) == 0 in every format);
* grad relative error vs an all-f32 reference is bounded per format at
  the real B/16 attention shape — the bf16 variant sits on the
  bf16-compute floor, the 8-bit variants within measured-and-padded
  bounds above it (PERF.md r6 records the exact measurements);
* quantized storage + attention dropout falls back to bf16 storage
  (warns once) instead of mis-packing dropout-rescaled weights;
* config/CLI validation rejects unknown formats.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_vit_paper_replication_tpu.configs import ViTConfig
from pytorch_vit_paper_replication_tpu.ops.attention import (
    _xla_attention, dot_product_attention)
from pytorch_vit_paper_replication_tpu.ops.quant import (
    PROBS_DTYPES, ROUNDTRIP_ABS_BOUND, dequantize_probs, quantize_probs,
    storage_bits)

NARROW = tuple(d for d in PROBS_DTYPES if d != "bf16")


def _qkv(seed, b, t, h, d, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d), dtype) for k in ks)


# --- pack/unpack primitives -----------------------------------------------


@pytest.mark.parametrize("name", PROBS_DTYPES)
def test_roundtrip_error_within_published_bound(name):
    w = jnp.linspace(0.0, 1.0, 4097, dtype=jnp.float32)
    back = dequantize_probs(quantize_probs(w, name), name, jnp.float32)
    err = float(jnp.max(jnp.abs(back - w)))
    bound = ROUNDTRIP_ABS_BOUND[name]
    assert err <= bound * (1 + 1e-6), (name, err, bound)


@pytest.mark.parametrize("name", PROBS_DTYPES)
def test_endpoints_exact(name):
    """0 and 1 — the masked-row zero and the one-hot prob — survive every
    format exactly (u8's exact-range scale, fp8/bf16 representable)."""
    w = jnp.array([0.0, 1.0], jnp.float32)
    back = dequantize_probs(quantize_probs(w, name), name, jnp.float32)
    np.testing.assert_array_equal(np.asarray(back), [0.0, 1.0])


def test_u8_is_256_level_exact_range():
    """u8 hits all 256 codes over [0,1] and inverts its own grid exactly."""
    grid = jnp.arange(256, dtype=jnp.float32) / 255.0
    codes = quantize_probs(grid, "u8")
    np.testing.assert_array_equal(np.asarray(codes), np.arange(256))
    back = dequantize_probs(codes, "u8", jnp.float32)
    np.testing.assert_allclose(np.asarray(back), np.asarray(grid),
                               rtol=0, atol=1e-7)


@pytest.mark.parametrize("name", PROBS_DTYPES)
def test_storage_bits(name):
    assert storage_bits(name) == (16 if name == "bf16" else 8)


# --- the attention core ---------------------------------------------------


def test_bf16_probs_dtype_is_bit_identical():
    """The default ("bf16", None) must BE the pre-r6 path — outputs and
    grads bitwise equal to calls that never mention probs_dtype."""
    q, k, v = _qkv(0, 2, 64, 2, 32, jnp.bfloat16)

    def f_old(args):
        return (dot_product_attention(*args, impl="xla")
                .astype(jnp.float32) ** 2).sum()

    def f_new(args):
        return (dot_product_attention(*args, impl="xla",
                                      probs_dtype="bf16",
                                      residual_dtype=None)
                .astype(jnp.float32) ** 2).sum()

    out_old = dot_product_attention(q, k, v, impl="xla")
    out_new = dot_product_attention(q, k, v, impl="xla",
                                    probs_dtype="bf16")
    np.testing.assert_array_equal(np.asarray(out_old, np.float32),
                                  np.asarray(out_new, np.float32))
    g_old = jax.jit(jax.grad(f_old))((q, k, v))
    g_new = jax.jit(jax.grad(f_new))((q, k, v))
    for name, a, b in zip("qkv", g_new, g_old):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32),
                                      err_msg=f"d{name}")


def test_residual_only_mode_keeps_forward_bit_identical():
    """probs_dtype='bf16' + a narrow residual_dtype changes ONLY the
    backward: the forward output stays bitwise the pre-r6 result."""
    q, k, v = _qkv(1, 2, 96, 2, 32, jnp.bfloat16)
    ref = dot_product_attention(q, k, v, impl="xla")
    for rd in NARROW:
        out = dot_product_attention(q, k, v, impl="xla",
                                    probs_dtype="bf16", residual_dtype=rd)
        np.testing.assert_array_equal(np.asarray(out, np.float32),
                                      np.asarray(ref, np.float32),
                                      err_msg=rd)


@pytest.mark.parametrize("pd", PROBS_DTYPES)
def test_fully_masked_row_zero_across_dtypes(pd):
    """The saturating softmax's defined zero output for an all-masked row
    (flash-kernel agreement, PERF.md r5) survives every storage format:
    quantize(0) == 0 everywhere."""
    t = 32
    q, k, v = _qkv(2, 1, t, 2, 16)
    mask = jnp.ones((1, 1, t, t), bool).at[:, :, 5].set(False)
    out = _xla_attention(q, k, v, dropout_rate=0.0, dropout_rng=None,
                         deterministic=True, mask=mask, probs_dtype=pd)
    np.testing.assert_array_equal(np.asarray(out[:, 5]), 0.0)
    # Non-degenerate rows stay close to the unquantized result.
    ref = _xla_attention(q, k, v, dropout_rate=0.0, dropout_rng=None,
                         deterministic=True, mask=mask)
    np.testing.assert_allclose(np.asarray(out[:, :5]),
                               np.asarray(ref[:, :5]), rtol=0.15, atol=0.1)


# Measured grad rel-error vs the f32 reference at the real B/16 shape
# (b=8, t=197, h=12, dh=64, bf16 compute — tools/attn_bytes_ab.py, CPU
# and TPU agree to the platform-matmul noise floor; PERF.md r6):
#   bf16 ~5.8e-3 (the bf16-compute floor), fp8_e4m3 ~7.4e-2,
#   fp8_e5m2 ~5.2e-2, u8 ~1.5e-1. Bounds are ~2x the measurement: tight
#   enough that a broken pack/unpack (O(1) error) or a silently-dropped
#   custom_vjp fails, loose enough for platform noise.
GRAD_REL_BOUND = {
    "bf16": 1.5e-2,
    "fp8_e4m3": 1.5e-1,
    "fp8_e5m2": 1.1e-1,
    "u8": 3.0e-1,
}


@pytest.mark.parametrize("pd", PROBS_DTYPES)
def test_grad_error_vs_f32_reference_bounded(pd):
    b, t, h, dh = 2, 197, 4, 64
    ks = jax.random.split(jax.random.key(3), 4)
    q32, k32, v32 = (jax.random.normal(kk, (b, t, h, dh), jnp.float32)
                     for kk in ks[:3])
    ct = jax.random.normal(ks[3], (b, t, h, dh), jnp.float32)

    def ref(q, k, v):
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * dh ** -0.5
        w = jax.nn.softmax(logits, axis=-1)
        return jnp.sum(jnp.einsum("bhqk,bkhd->bqhd", w, v) * ct)

    ref_g = jax.jit(jax.grad(ref, argnums=(0, 1, 2)))(q32, k32, v32)

    def loss(q, k, v):
        out = _xla_attention(q, k, v, dropout_rate=0.0, dropout_rng=None,
                             deterministic=True, probs_dtype=pd)
        return jnp.sum(out.astype(jnp.float32) * ct)

    args = tuple(a.astype(jnp.bfloat16) for a in (q32, k32, v32))
    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(*args)
    for name, a, r in zip("qkv", g, ref_g):
        a = jnp.asarray(a, jnp.float32)
        assert bool(jnp.isfinite(a).all()), f"d{name} not finite"
        rel = float(jnp.linalg.norm(a - r) / jnp.linalg.norm(r))
        assert rel <= GRAD_REL_BOUND[pd], (pd, f"d{name}", rel)


def test_quantized_with_dropout_falls_back_to_bf16_storage():
    """attn-dropout weights are rescaled past 1.0 — outside the packing
    range — so quantized calls under dropout must take the bf16 path
    (identical results to probs_dtype='bf16' with the same rng)."""
    q, k, v = _qkv(4, 1, 64, 2, 32)
    kw = dict(dropout_rate=0.5, dropout_rng=jax.random.key(7),
              deterministic=False)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out_q = _xla_attention(q, k, v, probs_dtype="u8", **kw)
    assert any("does not compose with" in str(w.message) for w in caught)
    out_b = _xla_attention(q, k, v, probs_dtype="bf16", **kw)
    np.testing.assert_array_equal(np.asarray(out_q), np.asarray(out_b))


def test_unknown_formats_rejected():
    q, k, v = _qkv(5, 1, 16, 1, 8)
    with pytest.raises(ValueError, match="probs_dtype"):
        dot_product_attention(q, k, v, probs_dtype="int4")
    with pytest.raises(ValueError, match="residual_dtype"):
        dot_product_attention(q, k, v, residual_dtype="fp16")
    with pytest.raises(ValueError, match="attention_probs_dtype"):
        ViTConfig(attention_probs_dtype="int4")
    with pytest.raises(ValueError, match="attention_probs_residual_dtype"):
        ViTConfig(attention_probs_residual_dtype="fp16")


def test_model_trains_a_step_with_quantized_probs():
    """End-to-end config plumbing: a tiny ViT with u8 probs storage takes
    one real train step to a finite loss (the custom_vjp composes with
    the whole fwd+bwd+Adam machinery)."""
    from pytorch_vit_paper_replication_tpu import engine
    from pytorch_vit_paper_replication_tpu.configs import TrainConfig
    from pytorch_vit_paper_replication_tpu.data import synthetic_batch
    from pytorch_vit_paper_replication_tpu.models import ViT
    from pytorch_vit_paper_replication_tpu.optim import make_optimizer

    cfg = ViTConfig(image_size=32, patch_size=8, num_layers=2, num_heads=2,
                    embedding_dim=32, mlp_size=64, num_classes=3,
                    dtype="float32", attention_impl="xla",
                    attention_probs_dtype="u8")
    model = ViT(cfg)
    rng = jax.random.key(0)
    params = model.init(rng, jnp.zeros((1, 32, 32, 3)))["params"]
    tx = make_optimizer(TrainConfig(warmup_fraction=0.1), total_steps=4)
    state = engine.TrainState.create(
        apply_fn=model.apply, params=params, tx=tx, rng=rng)
    step = jax.jit(engine.make_train_step())
    batch = jax.tree.map(jnp.asarray, synthetic_batch(4, 32, 3))
    state, metrics = step(state, batch)
    loss = float(metrics["loss_sum"]) / float(metrics["count"])
    assert np.isfinite(loss) and 0.0 < loss < 20.0, loss
