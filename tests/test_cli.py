"""CLI entry-point tests: drive ``train.main`` exactly as a user would
(reference entry points are notebooks + a broken ``train.py``; SURVEY.md
§2.1 — ours must actually work, on any mesh)."""

import math

import pytest

# The package exports engine.train as `train`, so import the CLI module's
# main explicitly.
from pytorch_vit_paper_replication_tpu.train import main as train_main


def test_cli_synthetic_seq_parallel(devices, tmp_path):
    """--mesh-seq 2: the whole CLI path trains with ring attention (gap
    pooling for an even token count) on a data=4 x seq=2 mesh."""
    results = train_main([
        "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "32",
        "--patch-size", "16", "--pool", "gap", "--dtype", "float32",
        "--attention", "xla", "--epochs", "1", "--batch-size", "8",
        "--mesh-data", "4", "--mesh-seq", "2",
        "--metrics-jsonl", str(tmp_path / "m.jsonl"),
    ])
    assert len(results["train_loss"]) == 1
    assert math.isfinite(results["train_loss"][0])
    assert (tmp_path / "m.jsonl").exists()


def test_cli_rejects_indivisible_batch(devices):
    """ADVICE r1: --batch-size not divisible by the data axis must be a
    clear CLI error, not an obscure sharding failure."""
    with pytest.raises(SystemExit, match="data"):
        train_main([
            "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "32",
            "--epochs", "1", "--batch-size", "6", "--mesh-data", "4",
            "--mesh-model", "2",
        ])


def test_cli_rejects_cls_pool_on_seq_mesh(devices):
    """CLS pooling gives an odd token count; --mesh-seq must fail fast
    with the pool='gap' hint."""
    with pytest.raises(ValueError, match="gap"):
        train_main([
            "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "32",
            "--patch-size", "16", "--epochs", "1", "--batch-size", "8",
            "--mesh-data", "4", "--mesh-seq", "2",
        ])


def test_cli_cifar10_synthetic(devices, tmp_path):
    """VERDICT r1 #4 done-criterion: the CLI trains on (fake) CIFAR-10
    end-to-end — BASELINE.json benchmark config #2's pipeline."""
    results = train_main([
        "--dataset", "cifar10", "--synthetic", "--preset", "ViT-Ti/16",
        "--image-size", "32", "--patch-size", "16", "--dtype", "float32",
        "--epochs", "1", "--batch-size", "8", "--mesh-data", "8",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ])
    assert len(results["train_loss"]) == 1
    assert math.isfinite(results["train_loss"][0])
    assert (tmp_path / "ckpt" / "final").is_dir()


def test_cli_mid_epoch_resume_matches_uninterrupted(devices, tmp_path):
    """VERDICT r2 #1: resume through ``train.main`` itself.

    Round 2 shipped a double-skip — train.py wired BOTH the loader-level
    index skip and engine.train's (since-removed) ``skip_train_batches``,
    so a resumed run silently dropped up to a full epoch. This test drives
    the CLI exactly as a preempted user would: train with step-interval
    checkpoints, delete everything after a mid-epoch save to simulate the
    preemption, rerun the same command, and require the resumed run to
    reach the full step count with params bit-identical to an
    uninterrupted run. Under the round-2 bug the resumed run trains 1
    batch instead of 2 and this fails on both assertions.
    """
    import shutil

    import jax
    import numpy as np
    import orbax.checkpoint as ocp

    from pytorch_vit_paper_replication_tpu.checkpoint import Checkpointer
    from pytorch_vit_paper_replication_tpu.data import (
        make_synthetic_image_folder)

    train_dir, test_dir = make_synthetic_image_folder(
        tmp_path / "ds", train_per_class=8, test_per_class=2, image_size=32)
    # 24 train images, batch 8, drop_last -> 3 steps/epoch, 6 steps total.
    common = [
        "--train-dir", str(train_dir), "--test-dir", str(test_dir),
        "--preset", "ViT-Ti/16", "--image-size", "32", "--patch-size", "16",
        "--dtype", "float32", "--attention", "xla", "--epochs", "2",
        "--batch-size", "8", "--mesh-data", "8", "--seed", "7",
        "--num-workers", "1",
    ]
    ck_a, ck_b = tmp_path / "ckA", tmp_path / "ckB"
    train_main(common + ["--checkpoint-dir", str(ck_a)])

    interval = ["--checkpoint-dir", str(ck_b),
                "--checkpoint-every-steps", "2", "--keep-checkpoints", "20"]
    train_main(common + interval)
    # Preemption right after the step-4 save (mid-epoch 2: 1 of 3 batches
    # of that epoch trained): drop every later checkpoint + the final
    # export, leaving step 4 as latest.
    for d in ck_b.iterdir():
        if d.is_dir() and (d.name.isdigit() or d.name == "final"):
            if d.name == "final" or int(d.name) > 4:
                shutil.rmtree(d)
    ck = Checkpointer(ck_b)
    assert ck.latest_step() == 4
    ck.close()

    train_main(common + interval)  # resume

    ck = Checkpointer(ck_b)
    assert ck.latest_step() == 6, "resumed run must finish all 6 steps"
    ck.close()

    ckptr = ocp.StandardCheckpointer()
    try:
        params_a = ckptr.restore(ck_a / "final")
        params_b = ckptr.restore(ck_b / "final")
    finally:
        ckptr.close()
    leaves_a, leaves_b = (jax.tree.leaves(t) for t in (params_a, params_b))
    assert len(leaves_a) == len(leaves_b)
    for a, b in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cli_eval_only_matches_training_final_metrics(devices, tmp_path):
    """VERDICT r2 missing #2: score a saved model without training.

    ``--eval-only`` (no --train-dir needed) must reproduce the training
    run's final test metrics exactly — same checkpoint, same eval split,
    deterministic eval pass.
    """
    import numpy as np

    from pytorch_vit_paper_replication_tpu.data import (
        make_synthetic_image_folder)

    train_dir, test_dir = make_synthetic_image_folder(
        tmp_path / "ds", train_per_class=8, test_per_class=3, image_size=32)
    model_args = [
        "--preset", "ViT-Ti/16", "--image-size", "32", "--patch-size", "16",
        "--dtype", "float32", "--attention", "xla", "--batch-size", "8",
        "--mesh-data", "8", "--seed", "5", "--num-workers", "1",
    ]
    ck = tmp_path / "ckpt"
    results = train_main(model_args + [
        "--train-dir", str(train_dir), "--test-dir", str(test_dir),
        "--epochs", "1", "--checkpoint-dir", str(ck)])

    ev = train_main(model_args + [
        "--test-dir", str(test_dir), "--eval-only",
        "--checkpoint-dir", str(ck)])
    assert ev["train_loss"] == []
    np.testing.assert_allclose(ev["test_loss"][0], results["test_loss"][-1],
                               rtol=1e-6)
    assert ev["test_acc"][0] == results["test_acc"][-1]

    # The params-only final/ export path: remove the step checkpoints so
    # eval-only falls back to final/ — same params, same metrics.
    import shutil
    for d in ck.iterdir():
        if d.is_dir() and d.name.isdigit():
            shutil.rmtree(d)
    ev2 = train_main(model_args + [
        "--test-dir", str(test_dir), "--eval-only",
        "--checkpoint-dir", str(ck)])
    np.testing.assert_allclose(ev2["test_loss"][0], results["test_loss"][-1],
                               rtol=1e-6)

    with pytest.raises(SystemExit, match="checkpoint-dir"):
        train_main(model_args + ["--test-dir", str(test_dir), "--eval-only"])


def test_cli_tinyvgg(devices):
    """Reference script-entry parity: the CLI can train the TinyVGG
    baseline (going_modular train.py:39-43 — which crashes upstream)."""
    results = train_main([
        "--synthetic", "--model", "tinyvgg", "--hidden-units", "8",
        "--image-size", "64", "--dtype", "float32",
        "--epochs", "1", "--batch-size", "8", "--mesh-data", "8",
    ])
    assert len(results["train_loss"]) == 1
    assert math.isfinite(results["train_loss"][0])


def test_cli_synthetic_scale_and_noise_flags(devices, tmp_path):
    """--synthetic-per-class / --synthetic-noise (the knobs behind the
    committed runs/dynamics_r4 artifact) reach the generator: more images
    per class -> more steps per epoch, and the logger records the LR
    schedule for auditability."""
    import json

    results = train_main([
        "--synthetic", "--synthetic-per-class", "16",
        "--synthetic-noise", "120", "--preset", "ViT-Ti/16",
        "--image-size", "32", "--patch-size", "16", "--dtype", "float32",
        "--epochs", "1", "--batch-size", "8",
        "--metrics-jsonl", str(tmp_path / "m.jsonl"),
    ])
    assert len(results["train_loss"]) == 1
    # 3 classes x 16/class = 48 train images -> 6 batches of 8.
    rec = json.loads((tmp_path / "m.jsonl").read_text().splitlines()[-1])
    assert rec["step"] == 6
    # LR logged from the real schedule (end of the only epoch = end of
    # decay -> 0).
    assert rec["lr"] == pytest.approx(0.0, abs=1e-6)
