"""CLI entry-point tests: drive ``train.main`` exactly as a user would
(reference entry points are notebooks + a broken ``train.py``; SURVEY.md
§2.1 — ours must actually work, on any mesh)."""

import math

import pytest

# The package exports engine.train as `train`, so import the CLI module's
# main explicitly.
from pytorch_vit_paper_replication_tpu.train import main as train_main

from conftest import requires_shard_map


@requires_shard_map
def test_cli_synthetic_seq_parallel(devices, tmp_path):
    """--mesh-seq 2: the whole CLI path trains with ring attention (gap
    pooling for an even token count) on a data=4 x seq=2 mesh."""
    results = train_main([
        "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "32",
        "--patch-size", "16", "--pool", "gap", "--dtype", "float32",
        "--attention", "xla", "--epochs", "1", "--batch-size", "8",
        "--mesh-data", "4", "--mesh-seq", "2",
        "--metrics-jsonl", str(tmp_path / "m.jsonl"),
    ])
    assert len(results["train_loss"]) == 1
    assert math.isfinite(results["train_loss"][0])
    assert (tmp_path / "m.jsonl").exists()


def test_cli_rejects_indivisible_batch(devices):
    """ADVICE r1: --batch-size not divisible by the data axis must be a
    clear CLI error, not an obscure sharding failure."""
    with pytest.raises(SystemExit, match="data"):
        train_main([
            "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "32",
            "--epochs", "1", "--batch-size", "6", "--mesh-data", "4",
            "--mesh-model", "2",
        ])


def test_cli_rejects_cls_pool_on_seq_mesh(devices):
    """CLS pooling gives an odd token count; --mesh-seq must fail fast
    with the pool='gap' hint."""
    with pytest.raises(ValueError, match="gap"):
        train_main([
            "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "32",
            "--patch-size", "16", "--epochs", "1", "--batch-size", "8",
            "--mesh-data", "4", "--mesh-seq", "2",
        ])


def test_cli_cifar10_synthetic(devices, tmp_path):
    """VERDICT r1 #4 done-criterion: the CLI trains on (fake) CIFAR-10
    end-to-end — BASELINE.json benchmark config #2's pipeline. Also
    rides the r5 ``--attention-softmax exact`` flag through the full
    stack (config plumb-through; the flavor itself is contract-tested
    in test_ops.py)."""
    results = train_main([
        "--dataset", "cifar10", "--synthetic", "--preset", "ViT-Ti/16",
        "--image-size", "32", "--patch-size", "16", "--dtype", "float32",
        "--attention-softmax", "exact",
        "--epochs", "1", "--batch-size", "8", "--mesh-data", "8",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ])
    assert len(results["train_loss"]) == 1
    assert math.isfinite(results["train_loss"][0])
    assert (tmp_path / "ckpt" / "final").is_dir()


def test_cli_mid_epoch_resume_matches_uninterrupted(devices, tmp_path):
    """VERDICT r2 #1: resume through ``train.main`` itself.

    Round 2 shipped a double-skip — train.py wired BOTH the loader-level
    index skip and engine.train's (since-removed) ``skip_train_batches``,
    so a resumed run silently dropped up to a full epoch. This test drives
    the CLI exactly as a preempted user would: train with step-interval
    checkpoints, delete everything after a mid-epoch save to simulate the
    preemption, rerun the same command, and require the resumed run to
    reach the full step count with params bit-identical to an
    uninterrupted run. Under the round-2 bug the resumed run trains 1
    batch instead of 2 and this fails on both assertions.
    """
    import shutil

    import jax
    import numpy as np
    import orbax.checkpoint as ocp

    from pytorch_vit_paper_replication_tpu.checkpoint import Checkpointer
    from pytorch_vit_paper_replication_tpu.data import (
        make_synthetic_image_folder)

    train_dir, test_dir = make_synthetic_image_folder(
        tmp_path / "ds", train_per_class=8, test_per_class=2, image_size=32)
    # 24 train images, batch 8, drop_last -> 3 steps/epoch, 6 steps total.
    common = [
        "--train-dir", str(train_dir), "--test-dir", str(test_dir),
        "--preset", "ViT-Ti/16", "--image-size", "32", "--patch-size", "16",
        "--dtype", "float32", "--attention", "xla", "--epochs", "2",
        "--batch-size", "8", "--mesh-data", "8", "--seed", "7",
        "--num-workers", "1",
    ]
    ck_a, ck_b = tmp_path / "ckA", tmp_path / "ckB"
    train_main(common + ["--checkpoint-dir", str(ck_a)])

    interval = ["--checkpoint-dir", str(ck_b),
                "--checkpoint-every-steps", "2", "--keep-checkpoints", "20"]
    train_main(common + interval)
    # Preemption right after the step-4 save (mid-epoch 2: 1 of 3 batches
    # of that epoch trained): drop every later checkpoint + the final
    # export, leaving step 4 as latest.
    for d in ck_b.iterdir():
        if d.is_dir() and (d.name.isdigit() or d.name == "final"):
            if d.name == "final" or int(d.name) > 4:
                shutil.rmtree(d)
    ck = Checkpointer(ck_b)
    assert ck.latest_step() == 4
    ck.close()

    train_main(common + interval)  # resume

    ck = Checkpointer(ck_b)
    assert ck.latest_step() == 6, "resumed run must finish all 6 steps"
    ck.close()

    ckptr = ocp.StandardCheckpointer()
    try:
        params_a = ckptr.restore(ck_a / "final")
        params_b = ckptr.restore(ck_b / "final")
    finally:
        ckptr.close()
    leaves_a, leaves_b = (jax.tree.leaves(t) for t in (params_a, params_b))
    assert len(leaves_a) == len(leaves_b)
    for a, b in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cli_eval_only_matches_training_final_metrics(devices, tmp_path):
    """VERDICT r2 missing #2: score a saved model without training.

    ``--eval-only`` (no --train-dir needed) must reproduce the training
    run's final test metrics exactly — same checkpoint, same eval split,
    deterministic eval pass.
    """
    import numpy as np

    from pytorch_vit_paper_replication_tpu.data import (
        make_synthetic_image_folder)

    train_dir, test_dir = make_synthetic_image_folder(
        tmp_path / "ds", train_per_class=8, test_per_class=3, image_size=32)
    model_args = [
        "--preset", "ViT-Ti/16", "--image-size", "32", "--patch-size", "16",
        "--dtype", "float32", "--attention", "xla", "--batch-size", "8",
        "--mesh-data", "8", "--seed", "5", "--num-workers", "1",
    ]
    ck = tmp_path / "ckpt"
    results = train_main(model_args + [
        "--train-dir", str(train_dir), "--test-dir", str(test_dir),
        "--epochs", "1", "--checkpoint-dir", str(ck)])

    ev = train_main(model_args + [
        "--test-dir", str(test_dir), "--eval-only",
        "--checkpoint-dir", str(ck)])
    assert ev["train_loss"] == []
    np.testing.assert_allclose(ev["test_loss"][0], results["test_loss"][-1],
                               rtol=1e-6)
    assert ev["test_acc"][0] == results["test_acc"][-1]

    # The params-only final/ export path: remove the step checkpoints so
    # eval-only falls back to final/ — same params, same metrics.
    import shutil
    for d in ck.iterdir():
        if d.is_dir() and d.name.isdigit():
            shutil.rmtree(d)
    ev2 = train_main(model_args + [
        "--test-dir", str(test_dir), "--eval-only",
        "--checkpoint-dir", str(ck)])
    np.testing.assert_allclose(ev2["test_loss"][0], results["test_loss"][-1],
                               rtol=1e-6)

    with pytest.raises(SystemExit, match="checkpoint-dir"):
        train_main(model_args + ["--test-dir", str(test_dir), "--eval-only"])


def test_cli_resume_schedule_horizon_guard(devices, tmp_path):
    """VERDICT r4 #6: extending a run past its recorded --epochs horizon
    re-scales the LR schedule (re-opening decay on a converged model —
    the epoch-31 loss spike of runs/longrun_r4) and must be an explicit
    choice, while a same-epochs resume must leave the LR trajectory
    bit-identical to the uninterrupted run."""
    import json
    import shutil

    from pytorch_vit_paper_replication_tpu.checkpoint import Checkpointer
    from pytorch_vit_paper_replication_tpu.data import (
        make_synthetic_image_folder)

    train_dir, test_dir = make_synthetic_image_folder(
        tmp_path / "ds", train_per_class=8, test_per_class=2, image_size=32)
    # 24 train images, batch 8, drop_last -> 3 steps/epoch.
    common = [
        "--train-dir", str(train_dir), "--test-dir", str(test_dir),
        "--preset", "ViT-Ti/16", "--image-size", "32", "--patch-size", "16",
        "--dtype", "float32", "--attention", "xla", "--batch-size", "8",
        "--mesh-data", "8", "--seed", "7", "--num-workers", "1",
    ]
    ck_a, ck_b = tmp_path / "ckA", tmp_path / "ckB"

    # Uninterrupted 2-epoch run: the reference LR trajectory.
    train_main(common + ["--epochs", "2", "--checkpoint-dir", str(ck_a),
                         "--metrics-jsonl", str(tmp_path / "a.jsonl")])
    lr_a = [json.loads(l)["lr"]
            for l in (tmp_path / "a.jsonl").read_text().splitlines()]

    # Same command, preempted after the step-4 mid-epoch save, resumed
    # with the SAME --epochs: the logged LR of the resumed epochs must
    # equal the uninterrupted run's exactly (no silent re-scaling).
    interval = ["--epochs", "2", "--checkpoint-dir", str(ck_b),
                "--checkpoint-every-steps", "2", "--keep-checkpoints", "20"]
    train_main(common + interval)
    for d in ck_b.iterdir():
        if d.is_dir() and (d.name.isdigit() or d.name == "final"):
            if d.name == "final" or int(d.name) > 4:
                shutil.rmtree(d)
    ck = Checkpointer(ck_b)
    assert ck.latest_step() == 4
    ck.close()
    train_main(common + interval
               + ["--metrics-jsonl", str(tmp_path / "b.jsonl")])
    lr_b = [json.loads(l)["lr"]
            for l in (tmp_path / "b.jsonl").read_text().splitlines()]
    # The resumed run logs epoch 2 only; it must match run A's epoch 2.
    assert lr_b[-1] == lr_a[-1]

    # Extending the finished run: --epochs 4 re-scales the schedule and
    # must be rejected without the explicit flag...
    with pytest.raises(SystemExit, match="extend-schedule"):
        train_main(common + ["--epochs", "4",
                             "--checkpoint-dir", str(ck_a)])
    # ...and accepted with it (reference main nb cell 98's manual
    # continuation), running the 2 additional epochs to the new horizon.
    results = train_main(common + ["--epochs", "4", "--extend-schedule",
                                   "--checkpoint-dir", str(ck_a),
                                   "--metrics-jsonl",
                                   str(tmp_path / "c.jsonl")])
    assert len(results["train_loss"]) == 2
    rec = json.loads((tmp_path / "c.jsonl").read_text().splitlines()[-1])
    # End of the re-scaled schedule -> LR decayed to 0 at the NEW horizon.
    assert rec["lr"] == pytest.approx(0.0, abs=1e-6)
    # The extended horizon is re-recorded: a further same-epochs resume
    # compares against 4, not 2.
    assert json.loads((ck_a / "run_meta.json").read_text())["epochs"] == 4


def test_cli_tinyvgg(devices):
    """Reference script-entry parity: the CLI can train the TinyVGG
    baseline (going_modular train.py:39-43 — which crashes upstream).
    Runs with ``--worker-type process`` so the forked-decode-worker path
    (reference DataLoader num_workers semantics, r5) is exercised through
    the full CLI stack in a live-JAX parent process."""
    results = train_main([
        "--synthetic", "--model", "tinyvgg", "--hidden-units", "8",
        "--image-size", "64", "--dtype", "float32",
        "--epochs", "1", "--batch-size", "8", "--mesh-data", "8",
        "--worker-type", "process", "--num-workers", "2",
    ])
    assert len(results["train_loss"]) == 1
    assert math.isfinite(results["train_loss"][0])


def test_cli_pretrained_resolution_change(devices, tmp_path):
    """VERDICT r4 #5 (CLI-level piece): the 384px/577-token transfer
    workflow's mechanics at test scale — torch-layout weights written for
    32px are fine-tuned through the CLI at 64px, so pos-embedding
    interpolation (2x2 -> 4x4 grid), frozen-backbone optimization, and
    the final export all execute via ``--pretrained``. The committed
    full-scale run is runs/transfer384_r5/ (B/16, 224->384, flash)."""
    import importlib.util
    from pathlib import Path as P

    import numpy as np
    import orbax.checkpoint as ocp

    torch = pytest.importorskip("torch")
    spec = importlib.util.spec_from_file_location(
        "make_torch_vit",
        P(__file__).resolve().parent.parent / "tools" / "make_torch_vit.py")
    mtv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mtv)

    from pytorch_vit_paper_replication_tpu.configs import PRESETS

    cfg32 = PRESETS["ViT-Ti/16"](num_classes=3, image_size=32)
    torch.manual_seed(0)
    pth = tmp_path / "ti_32.pth"
    torch.save(mtv.TorchViT(cfg32).state_dict(), pth)

    ck = tmp_path / "ckpt"
    results = train_main([
        "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "64",
        "--dtype", "float32", "--attention", "xla", "--ln-eps", "1e-5",
        "--epochs", "1", "--batch-size", "8", "--mesh-data", "8",
        "--num-workers", "1", "--pretrained", str(pth),
        "--freeze-backbone", "--checkpoint-dir", str(ck),
    ])
    assert math.isfinite(results["train_loss"][0])

    # The backbone really stayed frozen AND really came from the torch
    # weights: the exported conv kernel equals the converted torch one.
    ckptr = ocp.StandardCheckpointer()
    try:
        final = ckptr.restore(ck / "final")
    finally:
        ckptr.close()
    torch.manual_seed(0)  # reconstruct the identical source model
    want = mtv.TorchViT(cfg32)
    np.testing.assert_allclose(
        np.asarray(final["backbone"]["patch_embedding"]["patch_conv"]
                   ["kernel"]),
        want.state_dict()["conv_proj.weight"].numpy().transpose(2, 3, 1, 0),
        rtol=1e-6)
    # 64px config: pos table interpolated to 17 tokens (4x4 grid + CLS).
    assert final["backbone"]["patch_embedding"]["pos_embedding"].shape \
        == (1, 17, cfg32.embedding_dim)


def test_cli_synthetic_scale_and_noise_flags(devices, tmp_path):
    """--synthetic-per-class / --synthetic-noise (the knobs behind the
    committed runs/dynamics_r4 artifact) reach the generator: more images
    per class -> more steps per epoch, and the logger records the LR
    schedule for auditability."""
    import json

    results = train_main([
        "--synthetic", "--synthetic-per-class", "16",
        "--synthetic-noise", "120", "--preset", "ViT-Ti/16",
        "--image-size", "32", "--patch-size", "16", "--dtype", "float32",
        "--epochs", "1", "--batch-size", "8",
        "--metrics-jsonl", str(tmp_path / "m.jsonl"),
    ])
    assert len(results["train_loss"]) == 1
    # 3 classes x 16/class = 48 train images -> 6 batches of 8.
    rec = json.loads((tmp_path / "m.jsonl").read_text().splitlines()[-1])
    assert rec["step"] == 6
    # LR logged from the real schedule (end of the only epoch = end of
    # decay -> 0).
    assert rec["lr"] == pytest.approx(0.0, abs=1e-6)
