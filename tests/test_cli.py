"""CLI entry-point tests: drive ``train.main`` exactly as a user would
(reference entry points are notebooks + a broken ``train.py``; SURVEY.md
§2.1 — ours must actually work, on any mesh)."""

import math

import pytest

# The package exports engine.train as `train`, so import the CLI module's
# main explicitly.
from pytorch_vit_paper_replication_tpu.train import main as train_main


def test_cli_synthetic_seq_parallel(devices, tmp_path):
    """--mesh-seq 2: the whole CLI path trains with ring attention (gap
    pooling for an even token count) on a data=4 x seq=2 mesh."""
    results = train_main([
        "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "32",
        "--patch-size", "16", "--pool", "gap", "--dtype", "float32",
        "--attention", "xla", "--epochs", "1", "--batch-size", "8",
        "--mesh-data", "4", "--mesh-seq", "2",
        "--metrics-jsonl", str(tmp_path / "m.jsonl"),
    ])
    assert len(results["train_loss"]) == 1
    assert math.isfinite(results["train_loss"][0])
    assert (tmp_path / "m.jsonl").exists()


def test_cli_rejects_indivisible_batch(devices):
    """ADVICE r1: --batch-size not divisible by the data axis must be a
    clear CLI error, not an obscure sharding failure."""
    with pytest.raises(SystemExit, match="data"):
        train_main([
            "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "32",
            "--epochs", "1", "--batch-size", "6", "--mesh-data", "4",
            "--mesh-model", "2",
        ])


def test_cli_rejects_cls_pool_on_seq_mesh(devices):
    """CLS pooling gives an odd token count; --mesh-seq must fail fast
    with the pool='gap' hint."""
    with pytest.raises(ValueError, match="gap"):
        train_main([
            "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "32",
            "--patch-size", "16", "--epochs", "1", "--batch-size", "8",
            "--mesh-data", "4", "--mesh-seq", "2",
        ])


def test_cli_cifar10_synthetic(devices, tmp_path):
    """VERDICT r1 #4 done-criterion: the CLI trains on (fake) CIFAR-10
    end-to-end — BASELINE.json benchmark config #2's pipeline."""
    results = train_main([
        "--dataset", "cifar10", "--synthetic", "--preset", "ViT-Ti/16",
        "--image-size", "32", "--patch-size", "16", "--dtype", "float32",
        "--epochs", "1", "--batch-size", "8", "--mesh-data", "8",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ])
    assert len(results["train_loss"]) == 1
    assert math.isfinite(results["train_loss"][0])
    assert (tmp_path / "ckpt" / "final").is_dir()


def test_cli_tinyvgg(devices):
    """Reference script-entry parity: the CLI can train the TinyVGG
    baseline (going_modular train.py:39-43 — which crashes upstream)."""
    results = train_main([
        "--synthetic", "--model", "tinyvgg", "--hidden-units", "8",
        "--image-size", "64", "--dtype", "float32",
        "--epochs", "1", "--batch-size", "8", "--mesh-data", "8",
    ])
    assert len(results["train_loss"]) == 1
    assert math.isfinite(results["train_loss"][0])
