"""CLI entry-point tests: drive ``train.main`` exactly as a user would
(reference entry points are notebooks + a broken ``train.py``; SURVEY.md
§2.1 — ours must actually work, on any mesh)."""

import math

import pytest

# The package exports engine.train as `train`, so import the CLI module's
# main explicitly.
from pytorch_vit_paper_replication_tpu.train import main as train_main


def test_cli_synthetic_seq_parallel(devices, tmp_path):
    """--mesh-seq 2: the whole CLI path trains with ring attention (gap
    pooling for an even token count) on a data=4 x seq=2 mesh."""
    results = train_main([
        "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "32",
        "--patch-size", "16", "--pool", "gap", "--dtype", "float32",
        "--attention", "xla", "--epochs", "1", "--batch-size", "8",
        "--mesh-data", "4", "--mesh-seq", "2",
        "--metrics-jsonl", str(tmp_path / "m.jsonl"),
    ])
    assert len(results["train_loss"]) == 1
    assert math.isfinite(results["train_loss"][0])
    assert (tmp_path / "m.jsonl").exists()


def test_cli_rejects_indivisible_batch(devices):
    """ADVICE r1: --batch-size not divisible by the data axis must be a
    clear CLI error, not an obscure sharding failure."""
    with pytest.raises(SystemExit, match="data"):
        train_main([
            "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "32",
            "--epochs", "1", "--batch-size", "6", "--mesh-data", "4",
            "--mesh-model", "2",
        ])


def test_cli_rejects_cls_pool_on_seq_mesh(devices):
    """CLS pooling gives an odd token count; --mesh-seq must fail fast
    with the pool='gap' hint."""
    with pytest.raises(ValueError, match="gap"):
        train_main([
            "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "32",
            "--patch-size", "16", "--epochs", "1", "--batch-size", "8",
            "--mesh-data", "4", "--mesh-seq", "2",
        ])
