"""Native JPEG fast path (native/jpeg_loader.cc + ctypes bridge):
decode parity vs PIL, plan detection, and fallback behavior."""

import numpy as np
import pytest
from PIL import Image

from pytorch_vit_paper_replication_tpu import native
from pytorch_vit_paper_replication_tpu.data.transforms import (
    CenterCrop,
    Compose,
    NativePlan,
    Normalize,
    RandomHorizontalFlip,
    Resize,
    ResizeShorter,
    default_transform,
    eval_transform,
    native_plan,
    pretrained_transform,
    to_array,
)

needs_native = pytest.mark.skipif(
    not native.available(), reason="native decoder unavailable")


@pytest.fixture(scope="module")
def jpeg_path(tmp_path_factory):
    """A smooth non-square JPEG (resize-kernel differences stay small)."""
    rng = np.random.default_rng(0)
    base = rng.integers(0, 255, (15, 20, 3), np.uint8)
    img = Image.fromarray(base, "RGB").resize((600, 400), Image.BILINEAR)
    p = tmp_path_factory.mktemp("jpg") / "img.jpg"
    img.save(p, quality=92)
    return p


@needs_native
def test_squash_close_to_pil(jpeg_path):
    out = native.decode_jpeg_file(jpeg_path, 224, "squash")
    ref = np.asarray(Image.open(jpeg_path).resize((224, 224),
                                                  Image.BILINEAR))
    d = np.abs(out.astype(int) - ref.astype(int))
    assert out.shape == (224, 224, 3) and out.dtype == np.uint8
    assert d.mean() < 3 and d.max() < 48


@needs_native
def test_shorter_crop_close_to_pil(jpeg_path):
    out = native.decode_jpeg_file(jpeg_path, 224, "shorter_crop",
                                  resize=256)
    img = CenterCrop(224)(ResizeShorter(256)(Image.open(jpeg_path)))
    d = np.abs(out.astype(int) - np.asarray(img).astype(int))
    assert d.mean() < 3 and d.max() < 48


@needs_native
def test_same_size_decode_is_exact(tmp_path):
    """When no resample is needed the native path must equal PIL bitwise
    (both are the same libjpeg decode)."""
    rng = np.random.default_rng(1)
    p = tmp_path / "x.jpg"
    Image.fromarray(rng.integers(0, 255, (64, 64, 3), np.uint8),
                    "RGB").save(p, quality=90)
    out = native.decode_jpeg_file(p, 64, "squash")
    ref = np.asarray(Image.open(p).convert("RGB"))
    np.testing.assert_array_equal(out, ref)


@needs_native
def test_corrupt_data_returns_none():
    assert native.decode_jpeg(b"\xff\xd8not a real jpeg", 32) is None
    assert native.decode_jpeg(b"", 32) is None


@needs_native
def test_invalid_args_return_none(jpeg_path):
    data = jpeg_path.read_bytes()
    assert native.decode_jpeg(data, 0) is None          # bad target
    assert native.decode_jpeg(data, 64, "shorter_crop",
                              resize=32) is None        # crop > resize


def test_native_plan_detection():
    s = native_plan(default_transform(224))
    assert s == NativePlan("squash", 224, 224, True, None)

    e = native_plan(eval_transform(224, normalize=True))
    assert e.mode == "squash" and isinstance(e.normalize, Normalize)

    p = native_plan(pretrained_transform(224))
    assert p.mode == "shorter_crop" and (p.resize, p.crop) == (256, 224)

    # stochastic / unknown pipelines are not claimed
    aug = Compose([Resize(32), RandomHorizontalFlip(), to_array])
    assert native_plan(aug) is None
    assert native_plan(Compose([CenterCrop(10), to_array])) is None
    assert native_plan(to_array) is None


@needs_native
def test_dataset_fast_path_matches_pil(synthetic_folder):
    """ImageFolderDataset outputs match the PIL path (identical here: the
    synthetic JPEGs are already target-sized, so decode is resample-free)."""
    from pytorch_vit_paper_replication_tpu.data import ImageFolderDataset

    train_dir, _ = synthetic_folder
    fast = ImageFolderDataset(train_dir, default_transform(32))
    slow = ImageFolderDataset(train_dir, default_transform(32),
                              native_decode=False)
    assert fast._plan is not None and slow._plan is None
    for i in (0, 7, 17):
        a, la = fast[i]
        b, lb = slow[i]
        assert la == lb
        np.testing.assert_allclose(a, b, atol=2e-2)


@needs_native
def test_dataset_falls_back_for_non_jpeg(tmp_path):
    from pytorch_vit_paper_replication_tpu.data import ImageFolderDataset

    d = tmp_path / "cls_a"
    d.mkdir()
    rng = np.random.default_rng(2)
    Image.fromarray(rng.integers(0, 255, (40, 40, 3), np.uint8),
                    "RGB").save(d / "img.png")
    ds = ImageFolderDataset(tmp_path, default_transform(32))
    arr, label = ds[0]   # png: PIL path, must not error
    assert arr.shape == (32, 32, 3) and label == 0


@needs_native
def test_env_kill_switch(jpeg_path, monkeypatch):
    """PSR_TPU_NO_NATIVE disables the library for fresh loads."""
    import importlib

    monkeypatch.setenv("PSR_TPU_NO_NATIVE", "1")
    import pytorch_vit_paper_replication_tpu.native as nat
    state = (nat._lib, nat._tried)
    try:
        nat._lib, nat._tried = None, False
        assert not nat.available()
        assert nat.decode_jpeg_file(jpeg_path, 32) is None
    finally:
        nat._lib, nat._tried = state


@needs_native
def test_resize_crop_matches_pil():
    """psr_resize_crop ~= PIL crop+resize at the augmentation path's real
    reduction factors (<= pack_size/image_size ~= 1.14x; the native
    resampler does not antialias, so large reductions diverge from PIL's
    area-averaging filter by design — see resize_crop's docstring)."""
    rng = np.random.default_rng(3)
    base = rng.integers(0, 255, (20, 25, 3), np.uint8)
    arr = np.asarray(Image.fromarray(base).resize((300, 260),
                                                  Image.BILINEAR))
    out = native.resize_crop(arr, 13, 27, 180, 211, 224)
    ref = np.asarray(Image.fromarray(arr[13:193, 27:238]).resize(
        (224, 224), Image.BILINEAR))
    d = np.abs(out.astype(int) - ref.astype(int))
    assert d.mean() < 1 and d.max() <= 8


@needs_native
def test_resize_crop_rejects_bad_boxes():
    arr = np.zeros((50, 50, 3), np.uint8)
    assert native.resize_crop(arr, 0, 0, 60, 50, 32) is None   # box too tall
    assert native.resize_crop(arr, -1, 0, 10, 10, 32) is None  # negative
    assert native.resize_crop(arr, 45, 45, 10, 10, 32) is None # overflows
    assert native.resize_crop(
        arr.astype(np.float32), 0, 0, 10, 10, 32) is None      # wrong dtype


@needs_native
def test_resize_crop_does_not_bleed_outside_box():
    """Border output pixels must sample only inside the crop box (PIL
    crop().resize() semantics): a black box inside a white frame resizes
    to pure black, with zero bleed from the bright surround."""
    arr = np.full((100, 100, 3), 255, np.uint8)
    arr[40:72, 40:72] = 0
    out = native.resize_crop(arr, 40, 40, 32, 32, 48)  # upscale the box
    assert out is not None
    np.testing.assert_array_equal(out, 0)


@needs_native
def test_resize_crop_f32_bit_matches_composed_path():
    """Fused crop+flip+normalize == uint8 resize_crop, then flip, then the
    per-channel affine — bit-identical (the fused kernel rounds to the
    uint8 grid before scaling exactly so this holds)."""
    rng = np.random.default_rng(3)
    arr = rng.integers(0, 256, (96, 128, 3), np.uint8)
    scale = np.float32([0.1, 0.2, 0.3])
    off = np.float32([-1.0, 0.5, 2.0])
    u8 = native.resize_crop(arr, 5, 7, 80, 100, 64)
    ref = u8.astype(np.float32) * scale + off
    fused = native.resize_crop_f32(arr, 5, 7, 80, 100, 64,
                                   scale=scale, offset=off)
    np.testing.assert_array_equal(fused, ref)
    flipped = native.resize_crop_f32(arr, 5, 7, 80, 100, 64, hflip=True,
                                     scale=scale, offset=off)
    np.testing.assert_array_equal(flipped, ref[:, ::-1])


@needs_native
def test_u8_to_f32_matches_numpy():
    rng = np.random.default_rng(4)
    arr = rng.integers(0, 256, (50, 60, 3), np.uint8)
    scale = np.float32([0.01, 0.02, 0.03])
    off = np.float32([1.0, -2.0, 0.25])
    out = native.u8_to_f32(arr, scale, off)
    np.testing.assert_array_equal(out,
                                  arr.astype(np.float32) * scale + off)
    # scalar scale/offset broadcast (the normalize=False path)
    out2 = native.u8_to_f32(arr)
    np.testing.assert_allclose(out2, arr.astype(np.float32) / 255.0,
                               rtol=1e-6)


def test_fused_augment_matches_composed_transforms():
    """FusedAugmentArray (native or fallback) must produce the identical
    pixel stream to the r2 composed pipeline given the same RNG — crop
    box draw, flip draw, uint8-grid rounding, normalize constants."""
    from pytorch_vit_paper_replication_tpu.data.imagenet import (
        FusedAugmentArray, RandomHorizontalFlipArray,
        RandomResizedCropArray, ToFloatArray)

    rng = np.random.default_rng(11)
    arr = rng.integers(0, 256, (256, 256, 3), np.uint8)
    for seed in range(6):
        fused = FusedAugmentArray(224, normalize=True,
                                  rng=np.random.default_rng(seed))
        composed_rng = np.random.default_rng(seed)
        crop = RandomResizedCropArray(224, rng=composed_rng)
        flip = RandomHorizontalFlipArray(rng=composed_rng)
        to_float = ToFloatArray(normalize=True)
        got = fused(arr)
        want = to_float(np.ascontiguousarray(flip(crop(arr))))
        np.testing.assert_allclose(got, want, atol=1e-6)
