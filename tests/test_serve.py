"""Serving subsystem tests: bucket ladder, micro-batcher semantics
(deterministic — manual dispatch drive, no sleeps-as-sync), pad+mask
correctness, the checkpoint->serve round trip (bit-exact vs
``predict_image``, ``transform.json`` honored), bucketed directory
prediction, and the socket CLI."""

import json
import socket
import threading
import time

import numpy as np
import pytest

from pytorch_vit_paper_replication_tpu.serve import (
    DrainingError, InferenceEngine, MicroBatcher, QueueFullError,
    RequestExpired, ShutdownError, pad_rows_to_bucket, pick_bucket,
    plan_buckets)


# --------------------------------------------------------------- ladder
def test_pick_bucket_smallest_rung():
    assert pick_bucket(1) == 1
    assert pick_bucket(2) == 8
    assert pick_bucket(9, (1, 8, 32)) == 32
    with pytest.raises(ValueError, match="top bucket"):
        pick_bucket(257)


def test_plan_buckets_bounded_shapes_and_waste():
    """A 1000-image directory compiles <= 5 shapes (the satellite's
    done-criterion) and chunks cover every image exactly once."""
    plan = plan_buckets(1000)
    assert len(set(plan)) <= 5
    assert sum(plan) >= 1000
    assert sum(plan) - 1000 < plan[-1]  # waste < one final chunk
    # Sub-rung remainders pad up instead of spraying batch-of-1s...
    assert plan_buckets(7, (1, 8)) == [8]
    # ...but decompose when that wastes less total compute.
    assert plan_buckets(104) == [32, 32, 32, 8]
    assert plan_buckets(0) == []


def test_pad_rows_to_bucket_mask():
    rows = np.arange(6, dtype=np.float32).reshape(3, 2)
    padded, mask = pad_rows_to_bucket(rows, 8)
    assert padded.shape == (8, 2)
    np.testing.assert_array_equal(mask, [1, 1, 1, 0, 0, 0, 0, 0])
    np.testing.assert_array_equal(padded[:3], rows)
    full, mask_full = pad_rows_to_bucket(rows, 3)
    assert full is rows and mask_full.sum() == 3


# ---------------------------------------------------------- micro-batcher
def _echo_forward(log):
    def fwd(x, mask, heads):
        log.append((x.shape[0], int(mask.sum())))
        return x * 2.0
    return fwd


def _multihead_echo(log):
    """Head-splitting callback: the fused-forward output contract —
    a {head: per_row_outputs} dict covering every tagged head."""
    def fwd(x, mask, heads):
        log.append((x.shape[0], tuple(heads)))
        return {"probs": x * 2.0, "features": x * 3.0,
                "tokens": x * 5.0}
    return fwd


def test_batcher_coalesces_concurrent_submits():
    """Six submits inside one max-wait window ride ONE device batch
    (bucket 8), not six batch-of-1 dispatches."""
    log = []
    with MicroBatcher(_echo_forward(log), buckets=(1, 8, 32),
                      max_wait_us=300_000) as mb:
        futs = [mb.submit(np.full(4, i, np.float32)) for i in range(6)]
        outs = [f.result(timeout=10) for f in futs]
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(out, np.full(4, 2.0 * i))
    assert log == [(8, 6)]  # one padded bucket-8 batch, 6 real rows
    snap = mb.stats.snapshot()
    assert snap["counters"]["batches"] == 1
    assert snap["counters"]["padded_rows"] == 2
    assert snap["batch_occupancy"]["8"]["mean_occupancy"] == 0.75


def test_batcher_bucket_selection_deterministic():
    """Manual drive: batch size picks the smallest covering rung."""
    log = []
    mb = MicroBatcher(_echo_forward(log), buckets=(1, 4, 8),
                      max_wait_us=0, start_thread=False)
    mb.submit(np.zeros(2, np.float32))
    assert mb.run_once() == 1
    for _ in range(3):
        mb.submit(np.zeros(2, np.float32))
    assert mb.run_once() == 3
    assert [b for b, _ in log] == [1, 4]


def test_batcher_deadline_expiry_skips_device_batch():
    """An expired request is dropped at batch formation — the forward
    never sees its row — and its future fails with RequestExpired."""
    log = []
    mb = MicroBatcher(_echo_forward(log), buckets=(1, 4),
                      max_wait_us=0, start_thread=False)
    dead = mb.submit(np.full(2, 7.0, np.float32), timeout=0.0)
    time.sleep(0.002)  # guarantee monotonic() passes the deadline
    live = mb.submit(np.full(2, 1.0, np.float32))
    assert mb.run_once() == 1
    with pytest.raises(RequestExpired):
        dead.result(timeout=0)
    np.testing.assert_array_equal(live.result(timeout=0), np.full(2, 2.0))
    assert log == [(1, 1)]  # the expired row never occupied a batch
    assert mb.stats.snapshot()["counters"]["expired"] == 1


def test_batcher_degrades_and_recovers_bucket_cap():
    """Expiries step the bucket cap down a rung (drain faster); clean
    dispatches step it back up after `recover_after`."""
    mb = MicroBatcher(_echo_forward([]), buckets=(1, 4, 8),
                      max_wait_us=0, recover_after=2, start_thread=False)
    assert mb.effective_bucket_cap == 8
    mb.submit(np.zeros(2, np.float32), timeout=0.0)
    time.sleep(0.002)
    mb.submit(np.zeros(2, np.float32))
    mb.run_once()
    assert mb.effective_bucket_cap == 4  # degraded one rung
    for _ in range(2):  # two clean dispatches -> recover
        mb.submit(np.zeros(2, np.float32))
        mb.run_once()
    assert mb.effective_bucket_cap == 8


def test_batcher_full_queue_rejects_not_grows():
    mb = MicroBatcher(_echo_forward([]), buckets=(1,), max_queue=3,
                      start_thread=False)
    for _ in range(3):
        mb.submit(np.zeros(2, np.float32))
    with pytest.raises(QueueFullError) as exc:
        mb.submit(np.zeros(2, np.float32))
    assert exc.value.retry_after_s > 0
    assert mb.queue_depth() == 3  # rejected, not enqueued
    assert mb.stats.snapshot()["counters"]["rejected_queue_full"] == 1


def test_batcher_close_fails_pending_and_refuses_new():
    mb = MicroBatcher(_echo_forward([]), buckets=(4,), start_thread=False)
    fut = mb.submit(np.zeros(2, np.float32))
    mb.close()
    with pytest.raises(ShutdownError):
        fut.result(timeout=0)
    with pytest.raises(ShutdownError):
        mb.submit(np.zeros(2, np.float32))


def test_batcher_malformed_rows_fail_batch_not_batcher():
    """Mismatched row shapes break np.stack at batch FORMATION — that
    must fail the batch's futures, not kill the worker loop."""
    log = []
    mb = MicroBatcher(_echo_forward(log), buckets=(1, 4),
                      max_wait_us=0, start_thread=False)
    a = mb.submit(np.zeros(2, np.float32))
    b = mb.submit(np.zeros(3, np.float32))  # incompatible shape
    assert mb.run_once() == 2
    for fut in (a, b):
        with pytest.raises(ValueError):
            fut.result(timeout=0)
    assert log == []  # the forward never ran
    ok = mb.submit(np.ones(2, np.float32))  # batcher still serves
    mb.run_once()
    np.testing.assert_array_equal(ok.result(timeout=0), np.full(2, 2.0))


def test_batcher_cancelled_requests_do_not_break_dispatch():
    """A caller-cancelled future must not blow up resolution — neither
    at expiry (_collect), at close(), nor on a served batch."""
    mb = MicroBatcher(_echo_forward([]), buckets=(1, 4),
                      max_wait_us=0, start_thread=False)
    expired = mb.submit(np.zeros(2, np.float32), timeout=0.0)
    assert expired.cancel()
    time.sleep(0.002)
    served = mb.submit(np.zeros(2, np.float32))
    assert served.cancel()
    live = mb.submit(np.ones(2, np.float32))
    assert mb.run_once() == 2  # cancelled-but-live `served` + `live`
    np.testing.assert_array_equal(live.result(timeout=0), np.full(2, 2.0))
    closing = mb.submit(np.ones(2, np.float32))
    assert closing.cancel()
    mb.close()  # must not raise InvalidStateError


def test_engine_wrap_callback_error_fails_future_not_hangs():
    """An exception inside the result-wrapping callback (e.g. class_names
    shorter than the model's output row) must land on the returned
    future — cf swallows callback exceptions, which would otherwise
    leave the caller blocked forever."""
    import concurrent.futures as cf

    eng = InferenceEngine.__new__(InferenceEngine)  # no device needed
    eng.class_names = ["only"]
    raw: cf.Future = cf.Future()
    out = eng._wrap(raw)
    raw.set_result(np.array([0.1, 0.2, 0.7], np.float32))  # argmax = 2
    with pytest.raises(IndexError):
        out.result(timeout=1)


def test_batcher_forward_error_fails_batch_not_batcher():
    calls = {"n": 0}

    def fwd(x, mask, heads):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("device fell over")
        return x

    mb = MicroBatcher(fwd, buckets=(1, 4), max_wait_us=0,
                      start_thread=False)
    bad = mb.submit(np.zeros(2, np.float32))
    mb.run_once()
    with pytest.raises(RuntimeError, match="fell over"):
        bad.result(timeout=0)
    ok = mb.submit(np.ones(2, np.float32))
    mb.run_once()
    np.testing.assert_array_equal(ok.result(timeout=0), np.ones(2))


def test_batcher_drain_rejects_flushes_and_reports():
    """The first-class quiesce contract (ISSUE 10 satellite): drain
    refuses new submits with DrainingError (a QueueFullError carrying
    retry_after_s — existing backpressure handling applies), reports
    the unfinished count, and in-flight work keeps flushing."""
    mb = MicroBatcher(_echo_forward([]), buckets=(1, 4),
                      max_wait_us=0, start_thread=False)
    queued = [mb.submit(np.zeros(2, np.float32)) for _ in range(3)]
    # Manual-drive batcher: nothing consumes the queue, so a 0-budget
    # drain reports exactly the queued requests as unfinished.
    assert mb.drain(timeout_s=0.0) == 3
    assert mb.draining
    with pytest.raises(DrainingError) as exc:
        mb.submit(np.zeros(2, np.float32))
    assert exc.value.retry_after_s > 0
    assert isinstance(exc.value, QueueFullError)  # one backpressure
    #                                               taxonomy fleet-wide
    assert mb.stats.snapshot()["counters"]["rejected_draining"] == 1
    # Draining gates ADMISSION, not dispatch: the queue still flushes.
    assert mb.run_once() == 3
    for f in queued:
        np.testing.assert_array_equal(f.result(timeout=0), np.zeros(2))
    assert mb.drain(timeout_s=0.0) == 0   # now fully drained
    mb.resume()
    ok = mb.submit(np.ones(2, np.float32))
    mb.run_once()
    np.testing.assert_array_equal(ok.result(timeout=0), np.full(2, 2.0))


def test_batcher_drain_waits_for_worker_flush():
    """With the worker thread running, drain blocks until queued work
    lands (returns 0) instead of failing it like close() would."""
    with MicroBatcher(_echo_forward([]), buckets=(1, 8),
                      max_wait_us=100) as mb:
        futs = [mb.submit(np.full(2, i, np.float32)) for i in range(5)]
        assert mb.drain(timeout_s=10.0) == 0
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(
                f.result(timeout=0), np.full(2, 2.0 * i))


# --------------------------------------- multi-head + SLO tiers (ISSUE 12)
def test_batcher_coalesces_across_heads_one_dispatch():
    """Classifier and embedding requests inside one window ride ONE
    device batch; each future resolves to ITS head's row."""
    log = []
    mb = MicroBatcher(_multihead_echo(log), buckets=(1, 8),
                      max_wait_us=0, start_thread=False)
    futs = [mb.submit(np.full(2, i, np.float32), head=h)
            for i, h in enumerate(("probs", "features", "tokens",
                                   "probs"))]
    assert mb.run_once() == 4
    assert len(log) == 1   # ONE fused dispatch for the mixed batch
    assert log[0] == (8, ("probs", "features", "tokens", "probs"))
    scale = {"probs": 2.0, "features": 3.0, "tokens": 5.0}
    for i, (f, h) in enumerate(zip(futs, ("probs", "features",
                                          "tokens", "probs"))):
        np.testing.assert_array_equal(
            f.result(timeout=0), np.full(2, scale[h] * i))
    snap = mb.stats.snapshot()
    assert snap["counters"]["batches"] == 1
    assert snap["heads"]["probs"]["completed"] == 2
    assert snap["heads"]["features"]["completed"] == 1
    assert snap["heads"]["tokens"]["completed"] == 1


def test_batcher_missing_head_fails_request_not_batch():
    """A head the forward does not produce fails ITS future; siblings
    in the same batch still resolve — and the failure counts as
    head_errors, never as a completion in the per-head tables."""
    def fwd(x, mask, heads):
        return {"probs": x * 2.0}

    mb = MicroBatcher(fwd, buckets=(1, 4), max_wait_us=0,
                      start_thread=False)
    ok = mb.submit(np.ones(2, np.float32), head="probs")
    bad = mb.submit(np.ones(2, np.float32), head="features")
    assert mb.run_once() == 2
    np.testing.assert_array_equal(ok.result(timeout=0), np.full(2, 2.0))
    with pytest.raises(ValueError, match="no 'features' head"):
        bad.result(timeout=0)
    snap = mb.stats.snapshot()
    assert snap["counters"]["completed"] == 1
    assert snap["counters"]["head_errors"] == 1
    assert "features" not in {
        h for h, row in snap["heads"].items() if row["completed"]}


def test_batcher_deadline_shorter_than_fill_window_still_served():
    """A lone batch-tier request whose expiry deadline is SHORTER than
    the batch fill window must be dispatched off an idle device before
    it expires, not held for the fill window and then dropped."""
    log = []
    mb = MicroBatcher(_echo_forward(log), buckets=(1, 8),
                      max_wait_us=2000, batch_max_wait_us=300_000,
                      start_thread=False)
    fut = mb.submit(np.ones(2, np.float32), timeout=0.05, tier="batch")
    t0 = time.monotonic()
    assert mb.run_once() == 1
    assert time.monotonic() - t0 < 0.06   # not the 300 ms fill window
    np.testing.assert_array_equal(fut.result(timeout=0), np.full(2, 2.0))
    assert mb.stats.snapshot()["counters"]["expired"] == 0


def test_batcher_rejects_unknown_tier():
    mb = MicroBatcher(_echo_forward([]), buckets=(1,),
                      start_thread=False)
    with pytest.raises(ValueError, match="unknown tier"):
        mb.submit(np.zeros(2, np.float32), tier="bulk")


def test_batcher_batch_tier_waits_interactive_forces_dispatch():
    """Tiered batch-fill deadlines: a lone batch-tier request rides
    the queue for its (long) fill window; an interactive arrival caps
    the wait at max_wait — run_once returns as soon as the earliest
    fill deadline passes."""
    log = []
    mb = MicroBatcher(_echo_forward(log), buckets=(1, 8),
                      max_wait_us=0, batch_max_wait_us=60_000,
                      start_thread=False)
    t0 = time.monotonic()
    mb.submit(np.zeros(2, np.float32), tier="batch")
    assert mb.run_once() == 1
    waited = time.monotonic() - t0
    assert waited >= 0.05   # rode the 60 ms batch window (minus jitter)
    # Interactive company collapses the wait to max_wait (~0 here).
    mb.submit(np.zeros(2, np.float32), tier="batch")
    mb.submit(np.zeros(2, np.float32), tier="interactive")
    t0 = time.monotonic()
    assert mb.run_once() == 2   # one batch, both tiers coalesced
    assert time.monotonic() - t0 < 0.05


def test_batcher_interactive_wins_slots_batch_never_starves():
    """Priority at batch formation: interactive requests take the
    bucket slots first; a batch-tier request older than its fill
    window ESCALATES and can no longer be displaced."""
    log = []
    mb = MicroBatcher(_echo_forward(log), buckets=(1, 2),
                      max_wait_us=0, batch_max_wait_us=30_000,
                      start_thread=False)
    slow = mb.submit(np.zeros(2, np.float32), tier="batch")
    fast = [mb.submit(np.ones(2, np.float32)) for _ in range(2)]
    assert mb.run_once() == 2          # cap 2: both interactive win
    assert all(f.done() for f in fast)
    assert not slow.done()             # batch-tier displaced, queued
    time.sleep(0.04)                   # its 30 ms fill window passes
    more = [mb.submit(np.ones(2, np.float32)) for _ in range(2)]
    assert mb.run_once() == 2
    assert slow.done()                 # escalated: dispatched FIRST
    assert sum(f.done() for f in more) == 1   # one slot left
    mb.run_once()
    assert all(f.done() for f in more)


def test_batcher_tier_expiry_still_degrades():
    """The tier machinery composes with the existing degradation path:
    an expired batch-tier request sheds before occupying a batch AND
    steps the bucket cap down a rung, exactly like interactive expiry."""
    mb = MicroBatcher(_echo_forward([]), buckets=(1, 4, 8),
                      max_wait_us=0, recover_after=2,
                      start_thread=False)
    assert mb.effective_bucket_cap == 8
    dead = mb.submit(np.zeros(2, np.float32), timeout=0.0, tier="batch")
    time.sleep(0.002)
    live = mb.submit(np.zeros(2, np.float32))
    assert mb.run_once() == 1
    with pytest.raises(RequestExpired):
        dead.result(timeout=0)
    assert live.done()
    assert mb.effective_bucket_cap == 4   # degraded one rung
    snap = mb.stats.snapshot()
    assert snap["tiers"]["batch"]["expired"] == 1
    assert snap["counters"]["expired"] == 1


def test_batcher_segregated_mode_splits_heads():
    """The A/B baseline: segregate_heads=True runs the backbone once
    PER HEAD — the same admitted batch splits into per-head padded
    forwards (two fleets, same cadence) where the fused path runs one."""
    log = []
    mb = MicroBatcher(_multihead_echo(log), buckets=(1, 8),
                      max_wait_us=0, segregate_heads=True,
                      start_thread=False)
    p = [mb.submit(np.full(2, i, np.float32), head="probs")
         for i in range(2)]
    f = [mb.submit(np.full(2, i, np.float32), head="features")
         for i in range(2)]
    assert mb.run_once() == 4
    # TWO device dispatches for the mixed batch (vs the fused path's
    # one), each padded to its own bucket, each single-head.
    assert [entry[1] for entry in log] == [("probs", "probs"),
                                           ("features", "features")]
    for i, x in enumerate(p):
        np.testing.assert_array_equal(x.result(timeout=0),
                                      np.full(2, 2.0 * i))
    for i, x in enumerate(f):
        np.testing.assert_array_equal(x.result(timeout=0),
                                      np.full(2, 3.0 * i))
    snap = mb.stats.snapshot()
    assert snap["counters"]["batches"] == 2   # one per head


def test_engine_drain_cli_command(served_checkpoint, served_engine):
    """::drain quiesces through the engine and answers JSON; requests
    after it get DrainingError backpressure; resume() reopens."""
    from pytorch_vit_paper_replication_tpu.serve.__main__ import _answer

    _, train_dir, _ = served_checkpoint
    image = str(next(p for p in sorted(train_dir.rglob("*.jpg"))))
    try:
        reply = json.loads(_answer("::drain 5", served_engine, None))
        assert reply == {"draining": True, "unfinished": 0}
        with pytest.raises(DrainingError):
            served_engine.submit(np.zeros((32, 32, 3), np.float32))
        err = _answer(image, served_engine, None)
        assert "\tERROR\tDrainingError" in err
    finally:
        served_engine.resume()   # module-scoped engine: leave it open
    results = served_engine.predict(
        [np.zeros((32, 32, 3), np.float32)])
    assert len(results) == 1


def test_probs_cli_command_bit_identical(served_checkpoint,
                                         served_engine):
    """::probs answers the FULL softmax row, bit-identical to
    predict_image (what the fleet rollout's re-admission probe and
    fleet_bench's swapped-replica assert both rest on)."""
    from pytorch_vit_paper_replication_tpu.predictions import predict_image
    from pytorch_vit_paper_replication_tpu.serve.__main__ import _answer

    _, train_dir, classes = served_checkpoint
    image = next(p for p in sorted(train_dir.rglob("*.jpg")))
    _, _, probs_ref = predict_image(
        served_engine.model, served_engine._params, image, classes,
        transform=served_engine.transform)
    reply = json.loads(_answer(f"::probs {image}", served_engine, None))
    assert reply["label"] in classes
    got = np.asarray(reply["probs"], np.float32)
    np.testing.assert_array_equal(got, probs_ref)
    bad = json.loads(_answer("::probs /no/such/file.jpg",
                             served_engine, None))
    assert "error" in bad


def test_engine_fused_heads_bit_identity(served_checkpoint,
                                         served_engine):
    """ISSUE 12 parity satellite: the online pooled [D] embedding is
    bit-identical to (a) the OfflineEngine features head and (b) a
    direct ViTFeatureExtractor apply on the same checkpoint; the
    tokens head matches the raw backbone output; probs bit-identity
    vs predict_image is asserted by the existing round-trip test."""
    import jax
    import jax.numpy as jnp

    from pytorch_vit_paper_replication_tpu.models import (
        ViTFeatureExtractor)
    from pytorch_vit_paper_replication_tpu.serve import OfflineEngine

    assert served_engine.heads == ("probs", "features", "tokens")
    _, train_dir, _ = served_checkpoint
    images = sorted(train_dir.rglob("*.jpg"))[:3]
    rows = np.stack([served_engine._to_row(p) for p in images])

    # Bit-identity is a SAME-SHAPE contract (a different batch shape
    # is a different XLA program whose reductions may round
    # differently — the predict_batch test documents the same): each
    # online request below dispatches as a bucket-1 batch, so every
    # reference runs its program at batch shape 1 too.
    # (a) offline features head: the SAME checkpoint params through
    # OfflineEngine's own compiled program on a 1-device mesh.
    import jax as _jax
    off = OfflineEngine(served_engine.model, served_engine._params,
                        head="features",
                        image_size=served_engine.image_size,
                        buckets=(1,), devices=_jax.devices()[:1])
    assert off.ladder == (1,)

    # (b) direct backbone apply (pool + float32, the offline
    # expression, hand-rolled — proves both engines, not one vs other).
    cfg = served_engine.model.config
    backbone = ViTFeatureExtractor(cfg)

    def feat(p, x):
        tokens = backbone.apply({"params": p}, x)
        pooled = tokens[:, 0] if cfg.pool == "cls" else \
            tokens.mean(axis=1)
        return pooled.astype(jnp.float32)

    feat_fn = jax.jit(feat)
    tok_fn = jax.jit(
        lambda p, x: backbone.apply({"params": p}, x).astype(
            jnp.float32))

    for i, img in enumerate(images):
        online = served_engine.submit(img, head="features").result(
            timeout=30)
        off_row = np.asarray(off.dispatch(rows[i:i + 1]))[0]
        direct = np.asarray(feat_fn(
            served_engine._params["backbone"],
            jnp.asarray(rows[i:i + 1])))[0]
        np.testing.assert_array_equal(online, off_row)
        np.testing.assert_array_equal(online, direct)
        tokens = served_engine.submit(img, head="tokens",
                                      tier="batch").result(timeout=30)
        tok_direct = np.asarray(tok_fn(
            served_engine._params["backbone"],
            jnp.asarray(rows[i:i + 1])))[0]
        np.testing.assert_array_equal(tokens, tok_direct)


def test_engine_rejects_unknown_head(served_engine):
    with pytest.raises(ValueError, match="unknown head"):
        served_engine.submit(np.zeros((32, 32, 3), np.float32),
                             head="logits")


def test_cli_head_tier_protocol(served_checkpoint, served_engine):
    """The line protocol's multi-head surface: ::head/::tier set
    connection state, a features request answers full-precision JSON
    that reconstructs the served row bit-for-bit, and the one-shot
    ::req inline form needs no state."""
    from pytorch_vit_paper_replication_tpu.serve.__main__ import (
        ConnState, _answer)

    _, train_dir, _ = served_checkpoint
    image = str(next(p for p in sorted(train_dir.rglob("*.jpg"))))
    ref = served_engine.submit(image, head="features").result(timeout=30)

    state = ConnState()
    assert _answer("::head features", served_engine, None,
                   state) == "::head\tok\tfeatures"
    assert _answer("::tier batch", served_engine, None,
                   state) == "::tier\tok\tbatch"
    reply = _answer(image, served_engine, None, state)
    path, head, payload = reply.split("\t", 2)
    assert path == image and head == "features"
    got = np.asarray(json.loads(payload), np.float32)
    np.testing.assert_array_equal(got, ref)

    # Bad values keep the state and answer the ERROR shape.
    bad = _answer("::head logits", served_engine, None, state)
    assert "\tERROR\tValueError" in bad and state.head == "features"
    bad = _answer("::tier bulk", served_engine, None, state)
    assert "\tERROR\tValueError" in bad and state.tier == "batch"

    # One-shot ::req overrides a fresh connection's defaults; the
    # reply echoes the BARE path.
    fresh = ConnState()
    reply = _answer(f"::req head=tokens tier=batch {image}",
                    served_engine, None, fresh)
    path, head, payload = reply.split("\t", 2)
    assert path == image and head == "tokens"
    tok = np.asarray(json.loads(payload), np.float32)
    ref_tok = served_engine.submit(image, head="tokens").result(
        timeout=30)
    np.testing.assert_array_equal(tok, ref_tok)
    assert fresh.head == "probs"    # one-shot: state untouched
    bad = _answer("::req head=tokens", served_engine, None, fresh)
    assert "\tERROR\tValueError" in bad   # no path


def test_pipe_mode_head_tier_and_req(served_checkpoint, served_engine,
                                     monkeypatch, capsys):
    """The stdin/stdout pipe mode speaks the same multi-head surface:
    ::head/::tier flush the submit-ahead window and retag the stream;
    ::req rides the pipeline as a request."""
    import io

    from pytorch_vit_paper_replication_tpu.serve.__main__ import (
        _serve_stdin)

    _, train_dir, classes = served_checkpoint
    image = str(next(p for p in sorted(train_dir.rglob("*.jpg"))))
    ref = served_engine.submit(image, head="features").result(timeout=30)
    monkeypatch.setattr("sys.stdin", io.StringIO(
        f"{image}\n::head features\n{image}\n"
        f"::req head=probs tier=batch {image}\n"))
    _serve_stdin(served_engine, None)
    out = [ln for ln in capsys.readouterr().out.splitlines() if ln]
    assert len(out) == 4
    assert out[0].split("\t")[1] in classes          # default: probs TSV
    assert out[1] == "::head\tok\tfeatures"
    path, head, payload = out[2].split("\t", 2)
    assert path == image and head == "features"
    got = np.asarray(json.loads(payload), np.float32)
    # Protocol test, not bit-identity (that's pinned at controlled
    # shapes elsewhere): the pipelined features request may coalesce
    # with the ::req one into a different bucket shape = a different
    # XLA program (the predict_batch cross-shape caveat).
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.05)
    assert out[3].split("\t")[1] in classes          # ::req probs TSV


def test_pipe_mode_records_serve_request_root_span(
        served_checkpoint, served_engine, monkeypatch, capsys,
        tmp_path):
    """Pipelined stdin requests close a ``serve.request`` ROOT span
    (regression: the submit-ahead path minted the ingress context and
    the batcher wrote its children, but the root itself was never
    recorded — the merged tree held orphans)."""
    import io

    from pytorch_vit_paper_replication_tpu.serve.__main__ import (
        _serve_stdin)
    from pytorch_vit_paper_replication_tpu.telemetry.tracing import (
        configure_tracer)

    _, train_dir, _classes = served_checkpoint
    image = str(next(p for p in sorted(train_dir.rglob("*.jpg"))))
    sink = tmp_path / "sink_stdin.jsonl"
    configure_tracer(str(sink), role="replica", sample_rate=1.0)
    try:
        monkeypatch.setattr("sys.stdin", io.StringIO(f"{image}\n"))
        _serve_stdin(served_engine, None)
    finally:
        configure_tracer(None)
    out = [ln for ln in capsys.readouterr().out.splitlines() if ln]
    assert len(out) == 1 and "ERROR" not in out[0]
    rows = [json.loads(ln) for ln in
            sink.read_text().splitlines() if ln]
    roots = [r for r in rows if r["name"] == "serve.request"]
    assert len(roots) == 1
    root = roots[0]
    assert root["parent_id"] is None
    assert root["t1"] >= root["t0"]
    children = [r for r in rows if r["name"].startswith("batch.")]
    assert children, "batcher children missing from the sink"
    for ch in children:
        assert ch["trace_id"] == root["trace_id"]
        assert ch["parent_id"] == root["span_id"]
        # children nest inside the root's wall window (1 ms slack: the
        # monotonic/perf_counter epoch anchors are captured µs apart)
        assert root["t0"] <= ch["t0"] + 1e-3
        assert ch["t1"] <= root["t1"] + 1e-3


def test_stats_publish_head_tier_instruments(served_engine):
    """The serve_head_*/serve_tier_* instruments (ISSUE 12 satellite)
    ride ::metrics after mixed traffic."""
    from pytorch_vit_paper_replication_tpu.serve.__main__ import _answer

    row = np.zeros((32, 32, 3), np.float32)
    served_engine.submit(row, head="features",
                         tier="batch").result(timeout=30)
    served_engine.predict([row])
    text = _answer("::metrics", served_engine, None)
    assert "# TYPE vit_serve_head_features_total counter" in text
    assert "# TYPE vit_serve_tier_batch_total counter" in text
    assert "vit_serve_tier_batch_p99_s " in text
    snap = served_engine.snapshot()
    assert snap["heads"]["features"]["completed"] >= 1
    assert snap["tiers"]["batch"]["completed"] >= 1


def test_snapshot_model_tier_declared_overrides_arch(served_checkpoint,
                                                     served_engine):
    """``--model-tier``: an operator-declared deployment role wins
    over the arch-derived label in ::stats (a cascade's student
    replica reports "student", not just "ViT-Ti/16"); an undeclared
    engine keeps self-reporting its architecture."""
    ckpt, _, classes = served_checkpoint
    assert served_engine.snapshot()["model_tier"] == "ViT-Ti/16"
    eng = InferenceEngine.from_checkpoint(
        ckpt, preset="ViT-Ti/16", class_names=classes,
        buckets=(1,), warmup=False, use_manifest=False,
        model_tier="student")
    try:
        assert eng.snapshot()["model_tier"] == "student"
    finally:
        eng.close()


# ------------------------------------------------- pad+mask correctness
def test_pad_rows_never_change_real_logits(tiny_config):
    """Same real rows, same bucket shape, DIFFERENT pad contents ->
    bit-identical real-row outputs (rows of a ViT forward are
    independent; this is the property the mask contract rests on)."""
    import jax
    import jax.numpy as jnp

    from pytorch_vit_paper_replication_tpu.models import ViT

    model = ViT(tiny_config)
    rng = jax.random.key(0)
    s = tiny_config.image_size
    params = model.init(rng, jnp.zeros((1, s, s, 3)))["params"]
    fwd = jax.jit(lambda x: model.apply({"params": params}, x))

    real = np.asarray(
        jax.random.uniform(jax.random.key(1), (3, s, s, 3)), np.float32)
    pad_a, _ = pad_rows_to_bucket(real, 8)                 # row-0 pad
    pad_b = np.concatenate(
        [real, np.asarray(jax.random.uniform(jax.random.key(2),
                                             (5, s, s, 3)), np.float32)])
    out_a = np.asarray(fwd(jnp.asarray(pad_a)))[:3]
    out_b = np.asarray(fwd(jnp.asarray(pad_b)))[:3]
    np.testing.assert_array_equal(out_a, out_b)


# ---------------------------------------------- checkpoint -> serve trip
@pytest.fixture(scope="module")
def served_checkpoint(tmp_path_factory):
    """Train a tiny ViT 1 epoch through the real CLI (writes the final
    export + transform.json exactly like production) and return
    (checkpoint_dir, train_dir, class_names)."""
    from pytorch_vit_paper_replication_tpu.data import (
        make_synthetic_image_folder)
    from pytorch_vit_paper_replication_tpu.train import main as train_main

    root = tmp_path_factory.mktemp("serve_ckpt")
    train_dir, test_dir = make_synthetic_image_folder(
        root / "ds", train_per_class=4, test_per_class=2, image_size=32)
    train_main([
        "--train-dir", str(train_dir), "--test-dir", str(test_dir),
        "--preset", "ViT-Ti/16", "--image-size", "32", "--patch-size",
        "16", "--dtype", "float32", "--attention", "xla", "--epochs", "1",
        "--batch-size", "8", "--mesh-data", "8", "--num-workers", "1",
        "--checkpoint-dir", str(root / "ckpt"),
    ])
    classes = sorted(d.name for d in train_dir.iterdir() if d.is_dir())
    return root / "ckpt", train_dir, classes


@pytest.fixture(scope="module")
def served_engine(served_checkpoint):
    ckpt, _, classes = served_checkpoint
    eng = InferenceEngine.from_checkpoint(
        ckpt, preset="ViT-Ti/16", class_names=classes,
        buckets=(1, 4, 8), max_wait_us=1000)
    yield eng
    eng.close()


def test_roundtrip_bit_exact_vs_predict_image(served_checkpoint,
                                              served_engine):
    """Engine probs == predict_image probs bit-for-bit on the same
    image (same params, same transform, same jitted expression)."""
    from pytorch_vit_paper_replication_tpu.predictions import predict_image

    _, train_dir, classes = served_checkpoint
    image = next(p for p in sorted(train_dir.rglob("*.jpg")))
    label_ref, prob_ref, probs_ref = predict_image(
        served_engine.model, served_engine._params, image, classes,
        transform=served_engine.transform)
    result = served_engine.submit(image).result(timeout=30)
    np.testing.assert_array_equal(result.probs, probs_ref)
    assert result.label == label_ref
    assert result.prob == prob_ref


def test_roundtrip_honors_transform_json(served_checkpoint, served_engine):
    """The engine preprocesses with the checkpoint's recorded transform
    (32px, scratch run => NO ImageNet normalize), not the predict
    default (224px, normalize ON)."""
    from pytorch_vit_paper_replication_tpu.data.transforms import (
        make_transform)

    ckpt, train_dir, _ = served_checkpoint
    spec = json.loads((ckpt / "transform.json").read_text())
    assert served_engine.image_size == spec["image_size"] == 32
    image = next(p for p in sorted(train_dir.rglob("*.jpg")))
    from PIL import Image
    with Image.open(image) as img:
        expect = np.asarray(make_transform(**spec)(img))
    got = served_engine._to_row(image)
    np.testing.assert_array_equal(got, expect)
    assert got.shape == (32, 32, 3)
    assert got.min() >= 0.0 and got.max() <= 1.0  # un-normalized [0,1]


def test_engine_warmup_then_no_new_shapes(served_engine):
    """Every dispatch after warmup hits a warmed bucket shape."""
    shapes = set()
    orig = served_engine._fwd

    def counting(p, x):
        shapes.add(x.shape[0])
        return orig(p, x)

    served_engine._fwd = counting
    try:
        results = served_engine.predict(
            [np.zeros((32, 32, 3), np.float32)] * 3)
    finally:
        served_engine._fwd = orig
    assert len(results) == 3
    assert shapes <= set(served_engine.buckets)


def test_predict_batch_uses_bucket_ladder(served_checkpoint, monkeypatch):
    """Directory prediction chunks onto the ladder (6 images on a
    (1, 4, 8) ladder dispatch exactly plan_buckets(6) shapes) and every
    result matches the single-image path."""
    import pytorch_vit_paper_replication_tpu.predictions as predictions

    ckpt, train_dir, classes = served_checkpoint
    images = sorted(train_dir.rglob("*.jpg"))[:6]
    eng = InferenceEngine.from_checkpoint(
        ckpt, preset="ViT-Ti/16", class_names=classes, warmup=False,
        use_manifest=False)  # ad-hoc ladder test; skip the shared manifest

    shapes = []
    real_jf = predictions._jitted_forward

    def spying_jf(model):
        fwd = real_jf(model)

        def wrapped(params, x):
            shapes.append(int(x.shape[0]))
            return fwd(params, x)
        return wrapped

    monkeypatch.setattr(predictions, "_jitted_forward", spying_jf)
    batched = predictions.predict_batch(
        eng.model, eng._params, images, classes,
        transform=eng.transform, buckets=(1, 4, 8))
    assert shapes == plan_buckets(6, (1, 4, 8))
    singles = [predictions.predict_image(
        eng.model, eng._params, p, classes,
        transform=eng.transform)[:2] for p in images]
    for (bl, bp), (sl, sp) in zip(batched, singles):
        assert bl == sl
        # Different batch shapes are different XLA programs; CPU
        # vectorization reorders float reductions at ~1e-5.
        assert bp == pytest.approx(sp, abs=1e-4)
    eng.close()


# ------------------------------------------------------------------ CLI
def test_socket_cli_serves_and_reports_stats(served_checkpoint):
    """End-to-end socket mode: concurrent clients get answers, ::stats
    returns a JSON snapshot."""
    from pytorch_vit_paper_replication_tpu.serve.__main__ import (
        _serve_socket)

    ckpt, train_dir, classes = served_checkpoint
    eng = InferenceEngine.from_checkpoint(
        ckpt, preset="ViT-Ti/16", class_names=classes, buckets=(1, 4),
        max_wait_us=5000,
        use_manifest=False)  # ad-hoc ladder test; skip the shared manifest
    image = str(next(p for p in sorted(train_dir.rglob("*.jpg"))))
    holder = {}
    ready = threading.Event()

    def on_ready(srv):
        holder["srv"] = srv
        ready.set()

    t = threading.Thread(target=_serve_socket,
                         args=(eng, "127.0.0.1", 0, None, on_ready),
                         daemon=True)
    t.start()
    assert ready.wait(30)
    port = holder["srv"].server_address[1]

    def ask(line):
        with socket.create_connection(("127.0.0.1", port), timeout=30) as s:
            s.sendall((line + "\n").encode())
            return s.makefile().readline().strip()

    replies = []
    threads = [threading.Thread(
        target=lambda: replies.append(ask(image))) for _ in range(3)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(30)
    assert len(replies) == 3
    for r in replies:
        path, label, prob = r.split("\t")
        assert path == image and label in classes
        assert 0.0 <= float(prob) <= 1.0
    stats = json.loads(ask("::stats"))
    assert stats["counters"]["completed"] >= 3
    assert "latency_s" in stats and "buckets" in stats
    holder["srv"].shutdown()
    t.join(10)
    eng.close()


def test_predict_cli_classes_file(served_checkpoint, tmp_path, capsys):
    """--classes-file replaces greedy-nargs --classes and classifies."""
    from pytorch_vit_paper_replication_tpu.predict import main as predict_main

    ckpt, train_dir, classes = served_checkpoint
    cls_file = tmp_path / "classes.txt"
    cls_file.write_text("\n".join(classes) + "\n")
    image = str(next(p for p in sorted(train_dir.rglob("*.jpg"))))
    # Image path LAST — the arrangement greedy --classes silently eats.
    predict_main(["--checkpoint", str(ckpt), "--preset", "ViT-Ti/16",
                  "--classes-file", str(cls_file), image])
    out = capsys.readouterr().out
    assert image in out
    assert any(c in out for c in classes)


def test_cli_metrics_prometheus(served_engine):
    """The ::metrics command answers the shared telemetry registry as
    Prometheus text exposition — serve counters synced in, engine
    gauges included, TYPE headers well-formed (ISSUE 5)."""
    from pytorch_vit_paper_replication_tpu.serve.__main__ import _answer

    served_engine.predict([np.zeros((32, 32, 3), np.float32)] * 2)
    text = _answer("::metrics", served_engine, None)
    # The multi-line block is framed by a trailing blank line (after
    # the transport's own newline) so pipelining clients can find the
    # end of the response on this line-per-response protocol.
    assert text.endswith("\n") and not text.endswith("\n\n")
    assert "# TYPE vit_serve_submitted_total counter" in text
    assert "# TYPE vit_serve_completed_total counter" in text
    assert "# TYPE vit_serve_queue_depth gauge" in text
    assert "# TYPE vit_serve_latency_total_p50_s gauge" in text
    # Counters carry the real totals (>= the two requests just served).
    submitted = next(
        line for line in text.splitlines()
        if line.startswith("vit_serve_submitted_total "))
    assert float(submitted.split()[1]) >= 2
    # Every sample line is "name[{labels}] value" — scrapeable shape.
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        assert name.startswith("vit_")
        float(value)


def test_serve_stats_emit_jsonl(tmp_path):
    """ServeStats.emit writes MetricsLogger-compatible JSONL."""
    from pytorch_vit_paper_replication_tpu.metrics import MetricsLogger
    from pytorch_vit_paper_replication_tpu.serve import ServeStats

    stats = ServeStats()
    stats.observe_latency("total", 0.01)
    stats.observe_batch(8, 6)
    logger = MetricsLogger(jsonl_path=tmp_path / "serve.jsonl")
    stats.emit(logger, phase="test")
    logger.close()
    rec = json.loads((tmp_path / "serve.jsonl").read_text().splitlines()[0])
    assert rec["lat_total_p50"] == pytest.approx(0.01)
    assert rec["occupancy_b8"] == 0.75
    assert rec["batches"] == 1 and rec["phase"] == "test"
