"""Transfer-learning tests: torch->Flax weight conversion verified
numerically against genuine torch modules (torch CPU is available; the
reference's torchvision layout is emulated with standard torch layers)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")

from pytorch_vit_paper_replication_tpu.configs import ViTConfig
from pytorch_vit_paper_replication_tpu.models import ViT
from pytorch_vit_paper_replication_tpu.transfer import (
    convert_torch_vit_state_dict,
    init_from_pretrained,
)

# ln_epsilon=1e-5 matches torch.nn.LayerNorm's default (the layers the
# ground-truth model below is built from).
CFG = ViTConfig(image_size=32, patch_size=8, num_layers=2, num_heads=2,
                embedding_dim=32, mlp_size=64, num_classes=3,
                dtype="float32", attn_dropout=0.0, mlp_dropout=0.0,
                embedding_dropout=0.0, ln_epsilon=1e-5)


class TorchMiniViT(torch.nn.Module):
    """A torchvision-layout ViT built from stock torch layers, used as the
    conversion ground truth (state_dict keys follow torchvision
    vit_b_16: conv_proj, class_token, encoder.pos_embedding,
    encoder.layers.encoder_layer_i.{ln_1,self_attention,ln_2,mlp}, heads)."""

    def __init__(self, cfg):
        super().__init__()
        d = cfg.embedding_dim
        self.conv_proj = torch.nn.Conv2d(3, d, cfg.patch_size,
                                         cfg.patch_size)
        self.class_token = torch.nn.Parameter(torch.randn(1, 1, d) * 0.02)

        class Encoder(torch.nn.Module):
            pass

        class Layer(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.ln_1 = torch.nn.LayerNorm(d)
                self.self_attention = torch.nn.MultiheadAttention(
                    d, cfg.num_heads, batch_first=True)
                self.ln_2 = torch.nn.LayerNorm(d)
                self.mlp = torch.nn.Sequential(
                    torch.nn.Linear(d, cfg.mlp_size), torch.nn.GELU(),
                    torch.nn.Dropout(0.0),
                    torch.nn.Linear(cfg.mlp_size, d), torch.nn.Dropout(0.0))

            def forward(self, x):
                y = self.ln_1(x)
                a, _ = self.self_attention(y, y, y, need_weights=False)
                x = x + a
                return x + self.mlp(self.ln_2(x))

        enc = Encoder()
        enc.pos_embedding = torch.nn.Parameter(
            torch.randn(1, cfg.seq_len, d) * 0.02)
        enc.layers = torch.nn.ModuleDict(
            {f"encoder_layer_{i}": Layer() for i in range(cfg.num_layers)})
        enc.ln = torch.nn.LayerNorm(d)
        self.encoder = enc
        self.heads = torch.nn.Linear(d, cfg.num_classes)

    def forward(self, x):  # x: NCHW
        b = x.shape[0]
        p = self.conv_proj(x).flatten(2).transpose(1, 2)  # [B, N, D]
        tok = torch.cat([self.class_token.expand(b, -1, -1), p], dim=1)
        tok = tok + self.encoder.pos_embedding
        for i in range(len(self.encoder.layers)):
            tok = self.encoder.layers[f"encoder_layer_{i}"](tok)
        tok = self.encoder.ln(tok)
        return self.heads(tok[:, 0])


@pytest.fixture(scope="module")
def torch_model():
    torch.manual_seed(0)
    return TorchMiniViT(CFG).eval()


def test_forward_parity_with_torch(torch_model):
    """Converted weights reproduce the torch model's logits — the strongest
    possible check that every transposition/reshape in
    convert_torch_vit_state_dict is right."""
    params = convert_torch_vit_state_dict(
        torch_model.state_dict(), CFG, include_head=True)
    model = ViT(CFG)

    x = np.random.default_rng(0).standard_normal(
        (2, CFG.image_size, CFG.image_size, 3)).astype(np.float32)
    with torch.no_grad():
        ref = torch_model(
            torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    got = np.asarray(model.apply(
        {"params": jax.tree.map(jnp.asarray, params)}, jnp.asarray(x)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_init_from_pretrained_fresh_head(torch_model):
    """Backbone adopted, head re-initialized (reference 'replace heads'
    step, main notebook cell 113)."""
    model = ViT(CFG)
    params = init_from_pretrained(model, CFG, torch_model.state_dict())
    sd = torch_model.state_dict()
    np.testing.assert_allclose(
        np.asarray(params["backbone"]["encoder_norm"]["scale"]),
        sd["encoder.ln.weight"].numpy(), rtol=1e-6)
    # Head is zero-init, NOT the torch head.
    assert float(np.abs(np.asarray(params["head"]["kernel"])).max()) == 0.0


def test_convert_rejects_wrong_depth(torch_model):
    bad_cfg = CFG.replace(num_layers=5)
    with pytest.raises(ValueError, match="blocks"):
        convert_torch_vit_state_dict(torch_model.state_dict(), bad_cfg)


def test_convert_rejects_unknown_layout():
    with pytest.raises(ValueError, match="unrecognized"):
        convert_torch_vit_state_dict({"some.random.key": np.zeros(3)}, CFG)


def test_convert_head_class_mismatch(torch_model):
    with pytest.raises(ValueError, match="classes"):
        convert_torch_vit_state_dict(
            torch_model.state_dict(), CFG.replace(num_classes=7),
            include_head=True)


def test_load_torch_file_roundtrip(tmp_path, torch_model):
    path = tmp_path / "model.pth"
    torch.save(torch_model.state_dict(), path)
    from pytorch_vit_paper_replication_tpu.transfer import load_torch_file

    sd = load_torch_file(path)
    assert "conv_proj.weight" in sd
    params = convert_torch_vit_state_dict(sd, CFG, include_head=True)
    assert params["backbone"]["patch_embedding"]["patch_conv"][
        "kernel"].shape == (8, 8, 3, 32)


def test_interpolate_pos_embedding_resolution_change(torch_model):
    """Porting 32px weights into a 64px config (paper §3.2, the reference's
    SWAG@384 workflow, exercises cells 49-63): the pos-embedding grid is
    bicubically interpolated 4x4 -> 8x8 and the converted model runs."""
    cfg64 = CFG.replace(image_size=64)          # 8x8 grid + CLS = 65 tokens
    params = convert_torch_vit_state_dict(
        torch_model.state_dict(), cfg64)
    pos = params["backbone"]["patch_embedding"]["pos_embedding"]
    assert pos.shape == (1, 65, CFG.embedding_dim)
    # CLS slot is carried over untouched.
    np.testing.assert_allclose(
        pos[0, 0], torch_model.state_dict()["encoder.pos_embedding"]
        .numpy()[0, 0], rtol=1e-6)
    model = ViT(cfg64)
    full = init_from_pretrained(model, cfg64, torch_model.state_dict())
    x = jnp.zeros((1, 64, 64, 3))
    out = model.apply({"params": jax.tree.map(jnp.asarray, full)}, x)
    assert out.shape == (1, CFG.num_classes)


def test_interpolate_pos_embedding_properties():
    from pytorch_vit_paper_replication_tpu.transfer import (
        interpolate_pos_embedding)

    d = 8
    # Constant embeddings stay constant under bicubic resize.
    pos = np.concatenate([np.zeros((1, 1, d), np.float32),
                          np.full((1, 16, d), 3.5, np.float32)], axis=1)
    out = interpolate_pos_embedding(pos, CFG.replace(
        image_size=64, embedding_dim=d, num_heads=2))
    assert out.shape == (1, 65, d)
    np.testing.assert_allclose(out[0, 1:], 3.5, rtol=1e-5)
    # Same-resolution is the identity.
    same = interpolate_pos_embedding(pos, CFG.replace(
        image_size=32, embedding_dim=d, num_heads=2))
    np.testing.assert_allclose(same, pos)
    # Grid-only source (gap-pool target drops CLS entirely).
    grid_only = np.random.default_rng(0).standard_normal(
        (1, 16, d)).astype(np.float32)
    out2 = interpolate_pos_embedding(grid_only, CFG.replace(
        image_size=64, embedding_dim=d, num_heads=2, pool="gap"))
    assert out2.shape == (1, 64, d)


def test_convert_to_gap_pool_drops_cls(torch_model):
    """A gap-pool target config has no cls_token param; conversion must
    omit it (and the CLS pos-embedding slot)."""
    cfg_gap = CFG.replace(pool="gap")
    params = convert_torch_vit_state_dict(torch_model.state_dict(), cfg_gap)
    pe = params["backbone"]["patch_embedding"]
    assert "cls_token" not in pe
    assert pe["pos_embedding"].shape == (1, 16, CFG.embedding_dim)
    model = ViT(cfg_gap)
    full = init_from_pretrained(model, cfg_gap, torch_model.state_dict())
    out = model.apply({"params": jax.tree.map(jnp.asarray, full)},
                      jnp.zeros((1, 32, 32, 3)))
    assert out.shape == (1, CFG.num_classes)


def test_finetune_pretrained_with_normalized_inputs(torch_model,
                                                    synthetic_folder):
    """VERDICT r1 missing #2 done-criterion: fine-tune converted torch
    weights end-to-end with the pretrained (normalized) input transform."""
    from pytorch_vit_paper_replication_tpu import engine
    from pytorch_vit_paper_replication_tpu.configs import TrainConfig
    from pytorch_vit_paper_replication_tpu.data import create_dataloaders
    from pytorch_vit_paper_replication_tpu.data.transforms import (
        make_transform)
    from pytorch_vit_paper_replication_tpu.optim import (
        head_only_label_fn, make_optimizer)

    train_dir, test_dir = synthetic_folder
    tf = make_transform(CFG.image_size, pretrained=True)
    train_dl, _, classes = create_dataloaders(
        train_dir, test_dir, tf, batch_size=6, num_workers=2, seed=3)
    assert len(classes) == CFG.num_classes

    model = ViT(CFG)
    params = init_from_pretrained(model, CFG, torch_model.state_dict())
    tx = make_optimizer(
        TrainConfig(learning_rate=1e-2, warmup_fraction=0.0,
                    freeze_backbone=True),
        total_steps=len(train_dl) * 2,
        trainable_label_fn=head_only_label_fn)
    state = engine.TrainState.create(apply_fn=model.apply, params=params,
                                     tx=tx, rng=jax.random.key(3))
    step = jax.jit(engine.make_train_step(), donate_argnums=0)
    losses = []
    for _ in range(2):
        for b in train_dl:
            state, m = step(state, jax.tree.map(jnp.asarray, b))
            losses.append(float(m["loss_sum"] / m["count"]))
    assert losses[-1] < losses[0]
    # Normalized inputs really flowed: the transform output is not [0,1].
    batch = next(iter(train_dl))
    assert float(np.min(batch["image"])) < -0.5
