"""ISSUE 13: device-sharded embedding search.

Contracts pinned here:

* the sharded scan is EXACT: scores and indices bit-equal to a NumPy
  float32 reference argsort on the 8-virtual-device mesh, for both
  metrics, including tie-breaks (lowest row id);
* the padded-query-tail contract: a query batch padded up the bucket
  ladder returns results bit-identical to the unpadded per-query
  loop, and pad rows never appear as neighbors;
* the index manifest contract (rows/dim/dtype/sha pinned, corrupt and
  mismatched refusals with guidance);
* ``NpySink`` records the completed matrix's sha256 into
  ``progress.json`` at finish, and ``tools/build_index.py`` verifies
  it — refusing torn/mismatched/unhashed sinks;
* the resumable build: interrupted at any durable boundary and
  resumed, the final index is BYTE-IDENTICAL to an unkilled build's;
* IVF: deterministic resumable k-means, recall@10 >= 0.95 at the
  default nprobe on a clustered corpus (recall 1.0 at full probe);
* the online path: ``engine.search`` == embed-offline-then-scan
  bit-for-bit; the ``::search`` / ``::req k=`` protocol on the serve
  CLI; the fleet router relaying ``::search`` through the one
  ``::req`` grammar.
"""

import hashlib
import importlib.util
import json
import os
import socket
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from pytorch_vit_paper_replication_tpu.search.index import (  # noqa: E402
    EmbeddingIndex, load_index_manifest, validate_index_manifest,
    write_index_manifest)
from pytorch_vit_paper_replication_tpu.search.ivf import (  # noqa: E402
    build_ivf, ivf_search, kmeans, recall_at_k)
from pytorch_vit_paper_replication_tpu.search.scan import (  # noqa: E402
    ShardedScanner, reference_topk, shard_rows)
from pytorch_vit_paper_replication_tpu.serve.batching import (  # noqa: E402
    parse_req_line, parse_search_line)
from pytorch_vit_paper_replication_tpu.serve.offline import (  # noqa: E402
    NpySink, sink_sha256, write_progress)


def _load_build_index():
    spec = importlib.util.spec_from_file_location(
        "build_index_under_test", REPO / "tools" / "build_index.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _corpus(rows=3001, dim=24, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((rows, dim)).astype(np.float32)


def _fabricate_source(src: Path, mat: np.ndarray, *,
                      with_sha=True) -> Path:
    """A completed batch-infer output dir: the REAL sink + the REAL
    manifest shape (incl. the completion digest unless testing the
    legacy-manifest path)."""
    src.mkdir(parents=True, exist_ok=True)
    rows, dim = mat.shape
    sink = NpySink(src / "outputs.npy", rows=rows, dim=dim)
    sink.write(0, mat)
    sink.close()
    payload = {"fingerprint": "fp-test", "head": "features",
               "total_records": rows, "out_dim": dim,
               "batch_size": rows, "ladder": [rows],
               "sink": "outputs.npy", "records_done": rows,
               "rows_written": rows, "preds_bytes": None}
    if with_sha:
        payload["sink_sha256"] = sink_sha256(src / "outputs.npy")
    write_progress(src, payload)
    return src


# ------------------------------------------------------------- scan
def test_shard_rows_covers_and_pads_evenly():
    spans = shard_rows(10, 8)
    assert spans[0] == (0, 2) and spans[-1] == (10, 10)
    assert sum(hi - lo for lo, hi in spans) == 10
    per = spans[0][1] - spans[0][0]
    assert all(hi - lo <= per for lo, hi in spans)
    assert shard_rows(16, 4) == [(0, 4), (4, 8), (8, 12), (12, 16)]
    with pytest.raises(ValueError):
        shard_rows(0, 4)


def test_sharded_scan_bit_equal_to_numpy_reference(devices):
    db = _corpus()
    q = _corpus(13, db.shape[1], seed=1)
    scanner = ShardedScanner(db, k_max=10, devices=devices)
    scores, ids = scanner.scan(q, 10)
    ref_s, ref_i = reference_topk(db, q, 10)
    assert np.array_equal(ids, ref_i)
    assert np.array_equal(scores, ref_s)


def test_cosine_scan_bit_equal_to_reference(devices):
    db = _corpus(997, 16)
    norms = np.linalg.norm(db, axis=1)
    q = _corpus(5, 16, seed=2)
    scanner = ShardedScanner(db, k_max=7, metric="cosine",
                             norms=norms, devices=devices)
    scores, ids = scanner.scan(q, 7)
    ref_s, ref_i = reference_topk(db, q, 7, metric="cosine",
                                  norms=norms)
    assert np.array_equal(ids, ref_i)
    assert np.array_equal(scores, ref_s)


def test_padded_query_tail_bit_identical_to_unpadded_loop(devices):
    """The ISSUE 13 padded-tail contract: Q=5 rides the 8-rung (3 pad
    rows), Q=13 splits 8+8 with pad — every real row's result must be
    bit-identical to scanning that query alone, and no result may
    reference a pad row (all ids are real row numbers)."""
    db = _corpus(501, 12)
    scanner = ShardedScanner(db, k_max=6, devices=devices,
                             query_buckets=(1, 8))
    for n in (5, 13):
        q = _corpus(n, 12, seed=n)
        scores, ids = scanner.scan(q, 6)
        assert scores.shape == (n, 6) and ids.shape == (n, 6)
        assert ids.min() >= 0 and ids.max() < db.shape[0]
        for j in range(n):
            s1, i1 = scanner.scan(q[j], 6)
            assert np.array_equal(s1[0], scores[j])
            assert np.array_equal(i1[0], ids[j])


def test_scan_tie_break_is_lowest_row_id(devices):
    """Duplicate rows produce exactly-tied scores; the merge must
    resolve them the way the reference argsort does (lowest id)."""
    base = _corpus(40, 8)
    db = np.concatenate([base, base[:16]])     # rows 40..55 dup 0..15
    q = base[:3]
    scanner = ShardedScanner(db, k_max=4, devices=devices)
    scores, ids = scanner.scan(q, 4)
    ref_s, ref_i = reference_topk(db, q, 4)
    assert np.array_equal(ids, ref_i)
    assert np.array_equal(scores, ref_s)


def test_scan_k_and_shape_validation(devices):
    db = _corpus(64, 8)
    scanner = ShardedScanner(db, k_max=10, devices=devices)
    with pytest.raises(ValueError, match="outside"):
        scanner.scan(db[:2], 0)
    with pytest.raises(ValueError, match="outside"):
        scanner.scan(db[:2], 11)
    with pytest.raises(ValueError, match="dim"):
        scanner.scan(np.zeros((1, 9), np.float32), 5)
    with pytest.raises(ValueError, match="metric"):
        ShardedScanner(db, metric="l2", devices=devices)


def test_scan_publishes_search_instruments(devices):
    from pytorch_vit_paper_replication_tpu.telemetry.registry import (
        TelemetryRegistry)

    reg = TelemetryRegistry()
    db = _corpus(128, 8)
    scanner = ShardedScanner(db, k_max=5, devices=devices,
                             registry=reg)
    scanner.scan(db[:3], 5)
    snap = reg.snapshot()
    assert snap["counters"]["search_queries_total"] == 3
    assert snap["counters"]["search_scans_total"] >= 1
    assert snap["gauges"]["search_index_rows"] == 128
    assert snap["gauges"]["search_devices"] == len(devices)
    assert snap["histograms"]["search_scan_s"]["count"] >= 1


def test_tiny_corpus_on_wide_mesh(devices):
    """Fewer rows than devices: empty shards exist, their -inf
    candidates never win, and k up to rows stays exact."""
    db = _corpus(5, 6)
    scanner = ShardedScanner(db, k_max=5, devices=devices)
    scores, ids = scanner.scan(db, 5)
    ref_s, ref_i = reference_topk(db, db, 5)
    assert np.array_equal(ids, ref_i)
    assert np.isfinite(scores).all()


# -------------------------------------------------- index manifest
def test_index_manifest_roundtrip_and_corrupt_refusal(tmp_path):
    write_index_manifest(tmp_path, {
        "rows": 10, "dim": 4, "dtype": "float32",
        "source": "outputs.npy", "source_sha256": "x" * 64,
        "metric": "ip"})
    manifest = load_index_manifest(tmp_path)
    assert manifest["version"] == 1
    validate_index_manifest(manifest)
    (tmp_path / "index.json").write_text("{not json")
    with pytest.raises(ValueError, match="rebuild"):
        load_index_manifest(tmp_path)
    assert load_index_manifest(tmp_path / "nowhere") is None
    with pytest.raises(ValueError, match="missing"):
        validate_index_manifest({"rows": 1})
    with pytest.raises(ValueError, match="metric"):
        validate_index_manifest({
            "rows": 1, "dim": 1, "dtype": "float32", "source": "s",
            "source_sha256": "x", "metric": "hamming"})


def test_embedding_index_refuses_swapped_sink(tmp_path):
    bi = _load_build_index()
    mat = _corpus(200, 8)
    src = _fabricate_source(tmp_path / "embed", mat)
    bi.run_build(src, tmp_path / "idx")
    # Replace the sink AFTER the build: shape moves, the open refuses.
    sink = NpySink(src / "outputs.npy", rows=100, dim=8)
    sink.write(0, mat[:100])
    sink.close()
    with pytest.raises(ValueError, match="rebuild"):
        EmbeddingIndex(tmp_path / "idx")


# ---------------------------------------- sha satellite + build_index
def test_offline_run_records_sink_sha256_at_completion(tmp_path):
    """The PR 7 loop closed: a COMPLETED offline job's progress.json
    carries the sink's sha256 (mid-run manifests don't), and it equals
    the streaming hash of the file."""
    import flax.linen as nn
    import jax

    from pytorch_vit_paper_replication_tpu.serve.offline import (
        OfflineEngine, load_progress)

    class Flat(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x.reshape((x.shape[0], -1)))

    model = Flat()
    params = model.init(jax.random.key(0),
                        np.zeros((1, 8, 8, 3), np.float32))["params"]
    rng = np.random.default_rng(0)
    images = rng.random((24, 8, 8, 3)).astype(np.float32)

    class DS:
        def __len__(self):
            return 24

        def __getitem__(self, i):
            return images[i], 0

    engine = OfflineEngine(model, params, head="probs", image_size=8,
                           buckets=(8,))
    out = tmp_path / "job"
    engine.run(DS(), out, batch_size=8, resume=False, log_every_s=0.0)
    manifest = load_progress(out)
    assert manifest["records_done"] == 24
    assert manifest["sink_sha256"] == sink_sha256(out / "outputs.npy")


def test_build_index_refuses_unverifiable_sources(tmp_path):
    bi = _load_build_index()
    mat = _corpus(100, 8)

    # Incomplete job
    src = _fabricate_source(tmp_path / "incomplete", mat)
    m = json.loads((src / "progress.json").read_text())
    m["records_done"] = 50
    (src / "progress.json").write_text(json.dumps(m))
    with pytest.raises(ValueError, match="incomplete"):
        bi.run_build(src, tmp_path / "i1")

    # Legacy manifest without a digest: refuse, unless --allow-unhashed
    src2 = _fabricate_source(tmp_path / "legacy", mat, with_sha=False)
    with pytest.raises(ValueError, match="allow-unhashed"):
        bi.run_build(src2, tmp_path / "i2")
    summary = bi.run_build(src2, tmp_path / "i2", allow_unhashed=True)
    assert summary["verified_sha256"] is False

    # Torn/replaced sink: digest mismatch refuses with guidance
    src3 = _fabricate_source(tmp_path / "torn", mat)
    mm = np.load(src3 / "outputs.npy", mmap_mode="r+")
    mm[0, 0] += 1.0
    mm.flush()
    del mm
    with pytest.raises(ValueError, match="digest mismatch"):
        bi.run_build(src3, tmp_path / "i3")

    # Not a batch-infer dir at all
    with pytest.raises(ValueError, match="progress.json"):
        bi.run_build(tmp_path / "empty", tmp_path / "i4")


def test_build_index_resume_identity_mismatch_refuses(tmp_path):
    bi = _load_build_index()
    src = _fabricate_source(tmp_path / "embed", _corpus(100, 8))
    bi.run_build(src, tmp_path / "idx", metric="ip")
    with pytest.raises(ValueError, match="different build"):
        bi.run_build(src, tmp_path / "idx", metric="cosine")
    # --fresh overrides
    bi.run_build(src, tmp_path / "idx", metric="cosine", fresh=True)
    assert EmbeddingIndex(tmp_path / "idx").metric == "cosine"


def _tree_digests(d: Path) -> dict:
    return {f.name: hashlib.sha256(f.read_bytes()).hexdigest()
            for f in sorted(Path(d).glob("*"))
            if f.name != "build_progress.json"}


@pytest.mark.parametrize("stop_after", [1, 2, 4, 7])
def test_build_index_interrupted_resume_byte_identical(tmp_path,
                                                       stop_after):
    """Kill the build at any durable boundary (the stop_after_steps
    hook stops exactly where a SIGKILL at that boundary would), rerun
    the same command, and the final index is byte-identical to an
    unkilled build's — the PR 7 discipline for index builds."""
    bi = _load_build_index()
    mat = _corpus(1200, 12, seed=5)
    src = _fabricate_source(tmp_path / "embed", mat)
    kwargs = dict(ivf_lists=8, kmeans_iters=4, chunk_rows=256,
                  checkpoint_every_s=0.0)
    bi.run_build(src, tmp_path / "clean", **kwargs)
    with pytest.raises(bi.BuildInterrupted):
        bi.run_build(src, tmp_path / "killed",
                     stop_after_steps=stop_after, **kwargs)
    assert not (tmp_path / "killed" / "index.json").exists()
    bi.run_build(src, tmp_path / "killed", **kwargs)
    assert _tree_digests(tmp_path / "clean") == \
        _tree_digests(tmp_path / "killed")


# --------------------------------------------------------------- IVF
def _clustered(rows=4000, dim=16, clusters=20, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((clusters, dim)).astype(
        np.float32) * 4.0
    assign = rng.integers(0, clusters, rows)
    return centers[assign] + rng.standard_normal(
        (rows, dim)).astype(np.float32)


def test_kmeans_deterministic_and_iteration_resumable():
    sample = _clustered(600, 8, 6)
    full = kmeans(sample, 6, iters=5, seed=3)
    again = kmeans(sample, 6, iters=5, seed=3)
    assert np.array_equal(full, again)
    part = kmeans(sample, 6, iters=3, seed=3)
    resumed = kmeans(sample, 6, iters=5, seed=3, centroids=part,
                     start_iter=3)
    assert np.array_equal(full, resumed)


def test_ivf_recall_gate_on_clustered_corpus(tmp_path):
    bi = _load_build_index()
    mat = _clustered()
    src = _fabricate_source(tmp_path / "embed", mat)
    bi.run_build(src, tmp_path / "idx", ivf_lists=24, kmeans_iters=6)
    index = EmbeddingIndex(tmp_path / "idx")
    rng = np.random.default_rng(9)
    q = mat[rng.choice(len(mat), 16, replace=False)] + \
        0.1 * rng.standard_normal((16, mat.shape[1])).astype(np.float32)
    _, exact_i = reference_topk(mat, q, 10)
    _, ivf_i = ivf_search(index, q, 10, nprobe=8)
    assert recall_at_k(ivf_i, exact_i) >= 0.95
    # Full probe degenerates to exact: recall exactly 1.0
    _, all_i = ivf_search(index, q, 10, nprobe=24)
    assert recall_at_k(all_i, exact_i) == 1.0


def test_ivf_requires_quantizer(tmp_path):
    bi = _load_build_index()
    src = _fabricate_source(tmp_path / "embed", _corpus(64, 8))
    bi.run_build(src, tmp_path / "idx")      # exact-only
    index = EmbeddingIndex(tmp_path / "idx")
    with pytest.raises(ValueError, match="ivf-lists"):
        ivf_search(index, _corpus(2, 8), 5)


def test_build_ivf_convenience_matches_streamed_build(tmp_path):
    """The in-memory helper and the chunk-streamed builder must agree
    (same sample, same seed, same Lloyd math)."""
    bi = _load_build_index()
    mat = _clustered(1500, 8, 10, seed=2)
    cents, assign = build_ivf(mat, 10, sample_rows=1024, iters=6,
                              seed=7)
    src = _fabricate_source(tmp_path / "embed", mat)
    bi.run_build(src, tmp_path / "idx", ivf_lists=10, kmeans_iters=6,
                 sample_rows=1024, seed=7, chunk_rows=333)
    index = EmbeddingIndex(tmp_path / "idx")
    assert np.array_equal(index.centroids, cents)
    assert np.array_equal(np.asarray(index.assignments), assign)


# ------------------------------------------------------ ::req grammar
def test_parse_req_line_k_forms():
    assert parse_req_line("::req k=5 a.jpg") == \
        (None, None, 5, None, "a.jpg")
    assert parse_req_line("::req head=features tier=batch k=12 b c") \
        == ("features", "batch", 12, None, "b c")
    assert parse_req_line("::req tier=batch x.jpg") == \
        (None, "batch", None, None, "x.jpg")
    assert parse_req_line("::req model=teacher k=3 a.jpg") == \
        (None, None, 3, "teacher", "a.jpg")
    assert parse_req_line("::req head=probs model=student y.png") == \
        ("probs", None, None, "student", "y.png")
    with pytest.raises(ValueError, match="positive integer"):
        parse_req_line("::req k=0 a.jpg")
    with pytest.raises(ValueError, match="positive integer"):
        parse_req_line("::req k=ten a.jpg")
    with pytest.raises(ValueError):
        parse_req_line("::req k=3")


def test_parse_search_line_shared_grammar():
    """The ONE ::search parser (serve CLI + router both import it)."""
    assert parse_search_line("::search 5 a.jpg") == (5, "a.jpg")
    assert parse_search_line("::search 12 path with spaces.png") == \
        (12, "path with spaces.png")
    for bad in ("::search", "::search 5", "::search zero a.jpg",
                "::search 0 a.jpg", "::search -1 a.jpg"):
        with pytest.raises(ValueError, match="positive integer"):
            parse_search_line(bad)


# ----------------------------------------------------- online engine
@pytest.fixture(scope="module")
def search_world(tmp_path_factory):
    """One tiny ViT + a 24-image corpus embedded through the REAL
    offline features path + a built index + an engine serving it."""
    import jax
    import jax.numpy as jnp

    from pytorch_vit_paper_replication_tpu import configs
    from pytorch_vit_paper_replication_tpu.models import ViT
    from pytorch_vit_paper_replication_tpu.serve.engine import (
        InferenceEngine)
    from pytorch_vit_paper_replication_tpu.serve.offline import (
        OfflineEngine)

    bi = _load_build_index()
    work = tmp_path_factory.mktemp("search_world")
    cfg = configs.vit_ti16(num_classes=3, image_size=32,
                           dtype="float32", attention_impl="xla")
    model = ViT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 32, 32, 3)))["params"]
    rng = np.random.default_rng(0)
    images = rng.random((24, 32, 32, 3)).astype(np.float32)

    class DS:
        def __len__(self):
            return 24

        def __getitem__(self, i):
            return images[i], 0

    offline = OfflineEngine(model, params, head="features",
                            image_size=32, buckets=(8,))
    src = work / "embed"
    offline.run(DS(), src, batch_size=8, resume=False, log_every_s=0.0)
    bi.run_build(src, work / "idx")
    engine = InferenceEngine(
        model, params, image_size=32, buckets=(1, 8),
        class_names=["a", "b", "c"], warmup=False,
        search_index=work / "idx", search_k_max=10)
    world = {"engine": engine, "offline": offline, "images": images,
             "src": src, "work": work, "model": model,
             "params": params}
    yield world
    engine.close()


def test_engine_search_bit_consistent_with_offline_scan(search_world):
    """Online ::search == embed offline (the SAME features program
    the index was built with, AT THE SERVING SHAPE — a lone request
    rides bucket 1, and the PR 12 features parity is a same-shape
    contract) + scan the SAME index — bit-for-bit."""
    import jax

    from pytorch_vit_paper_replication_tpu.serve.offline import (
        OfflineEngine)

    engine = search_world["engine"]
    q = search_world["images"][5]
    ids, scores = engine.search(q, 5)
    assert ids[0] == 5            # a corpus member's nearest is itself
    offline_q = OfflineEngine(
        search_world["model"], search_world["params"], head="features",
        image_size=32, buckets=(1,), devices=jax.devices()[:1])
    emb = np.asarray(offline_q.dispatch(np.asarray(q)[None]))[0]
    db = np.load(search_world["src"] / "outputs.npy", mmap_mode="r")
    scanner = ShardedScanner(db, k_max=10)
    ref_s, ref_i = scanner.scan(emb[None], 5)
    assert ids == [int(i) for i in ref_i[0]]
    assert scores == [float(s) for s in ref_s[0]]


def test_engine_search_bounds_and_no_index(search_world):
    import jax
    import jax.numpy as jnp

    from pytorch_vit_paper_replication_tpu.serve.engine import (
        InferenceEngine)

    engine = search_world["engine"]
    with pytest.raises(ValueError, match="outside"):
        engine.search(search_world["images"][0], 11)
    bare = InferenceEngine(
        search_world["model"], search_world["params"], image_size=32,
        buckets=(1, 8), class_names=["a", "b", "c"], warmup=False)
    try:
        with pytest.raises(ValueError, match="search-index"):
            bare.search(search_world["images"][0], 3)
    finally:
        bare.close()
    # dim mismatch: an index whose rows aren't this model's embeddings
    from pytorch_vit_paper_replication_tpu.search.index import (
        EmbeddingIndex)

    bi = _load_build_index()
    src = _fabricate_source(search_world["work"] / "wrongdim",
                            _corpus(16, 7))
    bi.run_build(src, search_world["work"] / "wrongdim_idx")
    with pytest.raises(ValueError, match="dim"):
        InferenceEngine(
            search_world["model"], search_world["params"],
            image_size=32, buckets=(1, 8), class_names=["a", "b", "c"],
            warmup=False,
            search_index=EmbeddingIndex(
                search_world["work"] / "wrongdim_idx"))


def test_serve_answer_search_protocol(search_world, tmp_path):
    """The ::search K <path> command and its ::req k= relay form on
    the serve CLI's one-line-in-one-line-out handler, including the
    error shapes."""
    from PIL import Image

    from pytorch_vit_paper_replication_tpu.serve.__main__ import (
        ConnState, _answer)

    engine = search_world["engine"]
    img = tmp_path / "probe.png"
    arr = (search_world["images"][5] * 255).astype(np.uint8)
    Image.fromarray(arr).save(img)

    reply = _answer(f"::search 3 {img}", engine, None, ConnState())
    path, tag, payload = reply.split("\t", 2)
    assert path == str(img) and tag == "search"
    parsed = json.loads(payload)
    assert parsed["k"] == 3
    assert len(parsed["ids"]) == 3 and len(parsed["scores"]) == 3
    # the ::req k= relay form answers identically
    relay = _answer(f"::req k=3 {img}", engine, None, ConnState())
    assert relay == reply
    # full-precision scores: parse -> float32 round-trips exactly
    direct_ids, direct_scores = engine.search(str(img), 3)
    assert parsed["ids"] == direct_ids
    assert np.array_equal(
        np.asarray(parsed["scores"], np.float32),
        np.asarray(direct_scores, np.float32))
    # error shapes
    assert "ERROR" in _answer("::search nope x.jpg", engine, None,
                              ConnState())
    assert "ERROR" in _answer("::search 0 x.jpg", engine, None,
                              ConnState())
    assert "ERROR" in _answer(f"::search 99 {img}", engine, None,
                              ConnState())
    missing = _answer("::search 3 /nonexistent.png", engine, None,
                      ConnState())
    assert "ERROR" in missing


def test_snapshot_carries_search_index(search_world):
    snap = search_world["engine"].snapshot()
    assert snap["search_index"]["rows"] == 24
    assert snap["search_index"]["metric"] == "ip"


# ----------------------------------------------------- router relay
def test_router_relays_search_through_req_grammar(tmp_path):
    """ISSUE 13 + ISSUE 10: ::search K <path> at the router relays as
    the one ::req k= grammar over the pooled stateless connections;
    the fake replica's echo proves which k/tier actually arrived, and
    a bad K answers at the router without touching a replica."""
    from pytorch_vit_paper_replication_tpu.serve.fleet import (
        FleetRouter, ReplicaManager, ReplicaSpec)
    from pytorch_vit_paper_replication_tpu.telemetry.registry import (
        TelemetryRegistry)

    fake = REPO / "tests" / "data" / "fake_replica.py"

    def factory(spec):
        return [sys.executable, str(fake), "--ckpt", spec.checkpoint]

    registry = TelemetryRegistry()
    manager = ReplicaManager(
        [ReplicaSpec(rid="r0", checkpoint=str(tmp_path / "ckA"))],
        command_factory=factory,
        env_factory=lambda spec: dict(os.environ),
        health_interval_s=0.05, stale_after_s=2.0, registry=registry)
    router = FleetRouter(manager, registry=registry)
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()

        def ask(lines):
            with socket.create_connection(router.address,
                                          timeout=20.0) as sock:
                sock.settimeout(20.0)
                rfile = sock.makefile("r", encoding="utf-8")
                out = []
                for line in lines:
                    sock.sendall((line + "\n").encode())
                    out.append(rfile.readline().rstrip("\n"))
                return out

        (reply,) = ask(["::search 7 img1.jpg"])
        path, tag, payload = reply.split("\t", 2)
        assert path == "img1.jpg" and tag == "search"
        assert json.loads(payload) == {"k": 7,
                                       "tag": "ckA:interactive"}
        # connection tier state rides the relay
        tier_replies = ask(["::tier batch", "::search 2 x.jpg"])
        assert tier_replies[0] == "::tier\tok\tbatch"
        assert json.loads(tier_replies[1].split("\t", 2)[2]) == \
            {"k": 2, "tag": "ckA:batch"}
        # the explicit ::req k= spelling from a client works too
        (req_reply,) = ask(["::req k=4 y.jpg"])
        assert json.loads(req_reply.split("\t", 2)[2])["k"] == 4
        # bad K answers at the router (no replica round trip)
        (bad,) = ask(["::search zero img.jpg"])
        assert "ERROR" in bad and "positive integer" in bad