"""Cold-start subsystem tests (ISSUE 4): persistent-compile-cache
config/salt/fingerprint, cache-hit INSTRUMENTATION across fresh
subprocesses (no wall clocks), warmup-manifest contracts, AOT warmup
observability, the cached-restart bit-identity extension of the serve
round trip, the coldstart bench harness, and the bench compact-gates
line-length bound."""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from pytorch_vit_paper_replication_tpu import compile_cache
from pytorch_vit_paper_replication_tpu.serve.engine import (
    load_warmup_manifest, validate_warmup_manifest, write_warmup_manifest)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module", autouse=True)
def _cache_config_guard():
    """Leave the process cache-less after this module: later test files
    must not keep writing entries into this module's tmp dirs."""
    yield
    import jax

    jax.config.update("jax_compilation_cache_dir", None)
    try:
        from jax.experimental.compilation_cache import compilation_cache
        compilation_cache.reset_cache()
    except Exception:  # noqa: BLE001
        pass


# ----------------------------------------------------- fingerprint/salt
def test_config_fingerprint_stable_and_order_insensitive(tiny_config):
    a = compile_cache.config_fingerprint(tiny_config, x=1, y="b")
    b = compile_cache.config_fingerprint(tiny_config, y="b", x=1)
    assert a == b and len(a) == 64


def test_config_fingerprint_sensitive_to_config(tiny_config):
    base = compile_cache.config_fingerprint(tiny_config)
    assert base != compile_cache.config_fingerprint(
        tiny_config.replace(dtype="bfloat16"))
    assert base != compile_cache.config_fingerprint(
        tiny_config.replace(num_layers=3))


def test_cache_salt_versioned_and_fingerprinted():
    from pytorch_vit_paper_replication_tpu import __version__

    s1 = compile_cache.cache_salt("abcdef0123456789")
    s2 = compile_cache.cache_salt("ffff")
    assert s1.startswith(f"v{__version__}-") and s1 != s2
    assert compile_cache.cache_salt("") == f"v{__version__}-any"


def test_configure_nests_under_salt(tmp_path):
    fp = compile_cache.config_fingerprint(model="x")
    resolved = compile_cache.configure(str(tmp_path / "cc"), fingerprint=fp)
    assert resolved == tmp_path / "cc" / compile_cache.cache_salt(fp)
    assert resolved.is_dir()
    # a different fingerprint lands in a DIFFERENT (empty) subdir: stale
    # entries can never be consulted by a changed config
    other = compile_cache.configure(
        str(tmp_path / "cc"),
        fingerprint=compile_cache.config_fingerprint(model="y"))
    assert other != resolved


def test_resolve_cache_dir_env_fallback(monkeypatch):
    monkeypatch.delenv(compile_cache.ENV_CACHE_DIR, raising=False)
    assert compile_cache.resolve_cache_dir(None) is None
    assert compile_cache.resolve_cache_dir("/x") == "/x"
    monkeypatch.setenv(compile_cache.ENV_CACHE_DIR, "/from_env")
    assert compile_cache.resolve_cache_dir(None) == "/from_env"
    assert compile_cache.resolve_cache_dir("/cli_wins") == "/cli_wins"


def test_seconds_since_process_start_positive_and_monotonic():
    a = compile_cache.seconds_since_process_start()
    b = compile_cache.seconds_since_process_start()
    assert 0 < a <= b


def test_warn_if_uncached_fires_once_on_tpu(monkeypatch):
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(compile_cache, "_warned_uncached", False)
    # No cache configured at all for this check.
    old = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        with pytest.warns(UserWarning, match="compile-cache-dir"):
            compile_cache.warn_if_uncached("test")
        # second call: silent (warn ONCE per process)
        compile_cache.warn_if_uncached("test")
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


def test_no_warn_on_cpu_backend(monkeypatch, recwarn):
    monkeypatch.setattr(compile_cache, "_warned_uncached", False)
    compile_cache.warn_if_uncached("test")  # backend here IS cpu
    assert not [w for w in recwarn.list
                if "compile-cache-dir" in str(w.message)]


# --------------------------------------- cross-process hit instrumentation
_CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp
from pytorch_vit_paper_replication_tpu import compile_cache as C
C.configure(sys.argv[1], fingerprint=sys.argv[2])
f = jax.jit(lambda x: (x @ x.T).sum())
f(jnp.ones((128, 128))).block_until_ready()
print(json.dumps(C.STATS.snapshot()))
"""


def _run_child(script_path, cache_dir, fingerprint) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, str(script_path), str(cache_dir), fingerprint],
        capture_output=True, text=True, timeout=120, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_second_process_hits_cache_and_salt_invalidates(tmp_path):
    """The satellite's contract, asserted via instrumentation (hit/miss
    counters), not wall clock: an identical fingerprint in a FRESH
    process hits every entry; a changed salt starts cold."""
    script = tmp_path / "child.py"
    script.write_text(_CHILD.format(repo=str(REPO)))
    cold = _run_child(script, tmp_path / "cc", "fp_a")
    assert cold["hits"] == 0 and cold["requests"] >= 1
    warm = _run_child(script, tmp_path / "cc", "fp_a")
    assert warm["requests"] >= 1
    assert warm["hits"] == warm["requests"] and warm["misses"] == 0
    # saved = stored compile time - retrieval time: can be slightly
    # NEGATIVE for sub-ms modules, so only assert it was recorded.
    assert isinstance(warm["compile_time_saved_s"], float)
    salted = _run_child(script, tmp_path / "cc", "fp_B")
    assert salted["hits"] == 0  # stale entries not resurrected


# ------------------------------------------------------ warmup manifest
def test_warmup_manifest_round_trip(tmp_path):
    p = write_warmup_manifest(tmp_path, fingerprint="abc",
                              buckets=(8, 1, 32), image_size=224,
                              dtype="bfloat16")
    assert p.name == "warmup.json"
    m = load_warmup_manifest(tmp_path)
    assert m["buckets"] == [1, 8, 32] and m["fingerprint"] == "abc"
    assert validate_warmup_manifest(
        m, fingerprint="abc", buckets=(1, 8, 32),
        image_size=224) == [1, 8, 32]
    assert load_warmup_manifest(tmp_path / "nope") is None


def test_warmup_manifest_rejects_fingerprint_mismatch(tmp_path):
    write_warmup_manifest(tmp_path, fingerprint="abc", buckets=(1, 8),
                          image_size=224, dtype="bfloat16")
    m = load_warmup_manifest(tmp_path)
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        validate_warmup_manifest(m, fingerprint="OTHER", buckets=(1, 8),
                                 image_size=224)
    with pytest.raises(ValueError, match="image_size"):
        validate_warmup_manifest(m, fingerprint="abc", buckets=(1, 8),
                                 image_size=384)


def test_warmup_manifest_refuses_ladder_disagreeing_with_plan_buckets(
        tmp_path):
    """A manifest rung plan_buckets would never dispatch on this ladder
    (5 pads to 8; 64 exceeds the top rung) is refused, not warmed."""
    write_warmup_manifest(tmp_path, fingerprint="abc", buckets=(1, 5),
                          image_size=224, dtype="bfloat16")
    with pytest.raises(ValueError, match="plan_buckets"):
        validate_warmup_manifest(load_warmup_manifest(tmp_path),
                                 fingerprint="abc", buckets=(1, 8),
                                 image_size=224)
    write_warmup_manifest(tmp_path, fingerprint="abc", buckets=(64,),
                          image_size=224, dtype="bfloat16")
    with pytest.raises(ValueError, match="plan_buckets"):
        validate_warmup_manifest(load_warmup_manifest(tmp_path),
                                 fingerprint="abc", buckets=(1, 8, 32),
                                 image_size=224)


def test_corrupt_manifest_guided_refusal_and_atomic_write(tmp_path):
    """A tampered/torn warmup.json refuses with delete-it guidance, not
    a raw JSONDecodeError traceback; our own writer can't produce one
    (temp-file + atomic replace, no .tmp debris left behind)."""
    (tmp_path / "warmup.json").write_text('{"fingerprint": "abc", "buck')
    with pytest.raises(ValueError, match="delete"):
        load_warmup_manifest(tmp_path)
    (tmp_path / "warmup.json").write_text("[1, 2]")
    with pytest.raises(ValueError, match="JSON object"):
        load_warmup_manifest(tmp_path)
    write_warmup_manifest(tmp_path, fingerprint="abc", buckets=(1,),
                          image_size=224, dtype="bfloat16")
    assert load_warmup_manifest(tmp_path)["buckets"] == [1]
    assert list(tmp_path.glob("*.tmp*")) == []


def test_configure_refuses_file_as_cache_dir(tmp_path):
    """The misparse symptom — a positional swallowed into
    --compile-cache-dir — dies with a diagnosis, not NotADirectoryError."""
    img = tmp_path / "img.jpg"
    img.write_bytes(b"\xff\xd8")
    with pytest.raises(ValueError, match="swallowed"):
        compile_cache.configure(str(img))


# ------------------------------------- engine: AOT warmup + cached restart
@pytest.fixture(scope="module")
def tiny_ckpt(tmp_path_factory):
    """A ViT-Ti/16@32 params export + transform.json, the from_checkpoint
    contract without the cost of a CLI training run."""
    import jax
    import jax.numpy as jnp

    from pytorch_vit_paper_replication_tpu.checkpoint import save_model
    from pytorch_vit_paper_replication_tpu.configs import PRESETS
    from pytorch_vit_paper_replication_tpu.models import ViT

    cfg = PRESETS["ViT-Ti/16"](num_classes=3, image_size=32)
    model = ViT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 32, 32, 3)))["params"]
    root = tmp_path_factory.mktemp("cs_ckpt")
    save_model(params, root, "final")
    (root / "transform.json").write_text(json.dumps(
        {"image_size": 32, "pretrained": False, "normalize": False}))
    return root, model, params


def test_cached_restart_engine_bit_identical_and_observable(
        tiny_ckpt, tmp_path):
    """The acceptance-criteria extension of the serve round trip: a
    SECOND engine built from the same checkpoint with the persistent
    cache enabled (a) really deserializes its rung executables from the
    cache (hit counters — not wall clock), (b) consumes the warmup
    manifest the first serve wrote, and (c) serves probs bit-identical
    to predict_image."""
    from pytorch_vit_paper_replication_tpu.predictions import predict_image
    from pytorch_vit_paper_replication_tpu.serve import InferenceEngine

    ckpt, model, params = tiny_ckpt
    fp = compile_cache.config_fingerprint(model.config, image_size=32)
    compile_cache.configure(str(tmp_path / "cache"), fingerprint=fp)
    assert load_warmup_manifest(ckpt) is None
    with InferenceEngine.from_checkpoint(
            ckpt, preset="ViT-Ti/16", num_classes=3, buckets=(1, 2),
            max_wait_us=500) as e1:
        snap = e1.snapshot()
    # first serve wrote the manifest; per-rung timings are observable
    manifest = load_warmup_manifest(ckpt)
    assert manifest["buckets"] == [1, 2]
    assert set(snap["warmup"]["rungs"]) == {"1", "2"}
    assert snap["warmup"]["done"] and snap["warmup"]["cumulative_s"] > 0
    assert snap["compile_cache"]["requests"] >= 2
    assert snap["warm_rungs"] == [1, 2]

    hits_before = compile_cache.STATS.hits
    with InferenceEngine.from_checkpoint(
            ckpt, preset="ViT-Ti/16", num_classes=3, buckets=(1, 2),
            max_wait_us=500) as e2:
        # the restart consumed the manifest's rung set from disk...
        assert e2._warmup_rungs == (1, 2)
        # ...its executables came from the persistent cache...
        assert compile_cache.STATS.hits - hits_before >= 2
        # ...and the numerics are untouched: bit-identical probs.
        import jax
        img = np.asarray(jax.random.uniform(jax.random.key(1), (32, 32, 3)),
                         np.float32)
        _, _, probs_ref = predict_image(model, params, img,
                                        ["a", "b", "c"], image_size=32)
        result = e2.submit(img).result(timeout=60)
        np.testing.assert_array_equal(result.probs, probs_ref)
        assert e2.snapshot()["time_to_first_batch_s"] > 0


def test_engine_refuses_manifest_from_other_model(tiny_ckpt, tmp_path):
    """from_checkpoint validates the on-disk manifest against THIS
    engine's fingerprint/ladder before warming anything."""
    import shutil

    from pytorch_vit_paper_replication_tpu.serve import InferenceEngine

    ckpt, _, _ = tiny_ckpt
    clone = tmp_path / "ckpt_clone"
    shutil.copytree(ckpt, clone)
    m = load_warmup_manifest(clone) or {}
    # write_warmup_manifest resolves the final/ subdir exactly like the
    # engine's read path, so the tampered file is the one it loads
    write_warmup_manifest(clone, fingerprint="someone-elses-model",
                          buckets=m.get("buckets", [1, 2]),
                          image_size=32, dtype="bfloat16")
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        InferenceEngine.from_checkpoint(clone, preset="ViT-Ti/16",
                                        num_classes=3, buckets=(1, 2),
                                        warmup=False)


def test_manifest_extends_with_dispatched_rungs(tiny_ckpt, tmp_path):
    """close() unions traffic-dispatched rungs into the manifest, so a
    widened ladder converges to warm on the next restart instead of
    fossilizing on the first serve's shape set — and the manifest is
    one file whether the checkpoint is addressed as the run dir or its
    final/ export."""
    import shutil

    from pytorch_vit_paper_replication_tpu.serve import InferenceEngine

    src, _, _ = tiny_ckpt
    ckpt = tmp_path / "ckpt"
    shutil.copytree(src, ckpt)
    for d in (ckpt, ckpt / "final"):
        (d / "warmup.json").unlink(missing_ok=True)
    eng = InferenceEngine.from_checkpoint(
        ckpt, preset="ViT-Ti/16", num_classes=3, buckets=(1, 2),
        warmup=False)
    assert load_warmup_manifest(ckpt) is None  # warmup=False: no write
    eng.stats.observe_batch(2, 2)  # traffic rides rung 2
    eng.close()
    m = load_warmup_manifest(ckpt)
    assert m["buckets"] == [2]
    # run-dir and final/ spellings resolve to the SAME manifest file
    assert load_warmup_manifest(ckpt / "final") == m
    assert not (ckpt / "warmup.json").exists()
    # a corrupt manifest doesn't crash manifest upkeep — it is repaired
    # from the dispatched set instead
    (ckpt / "final" / "warmup.json").write_text("{torn")
    eng._extend_manifest()
    assert load_warmup_manifest(ckpt)["buckets"] == [2]


def test_background_warmup_serves_before_ladder_finishes(tiny_ckpt):
    """warmup="async": submit() is servable immediately (jit fallback /
    early rungs) and the ladder converges to fully warm."""
    from pytorch_vit_paper_replication_tpu.serve import InferenceEngine as Eng

    ckpt, model, params = tiny_ckpt
    eng = Eng(model, params, image_size=32, class_names=["a", "b", "c"],
              buckets=(1, 2), warmup="async", max_wait_us=500)
    try:
        img = np.zeros((32, 32, 3), np.float32)
        r = eng.submit(img).result(timeout=60)
        assert r.probs.shape == (3,)
        assert eng.wait_warm(60)
        assert sorted(eng._compiled) == [1, 2]
        assert eng._warmup_error is None
    finally:
        eng.close()


# -------------------------------------------------- coldstart harness
def test_coldstart_serve_child_cold_then_warm(tiny_ckpt, tmp_path):
    """The tools/coldstart_bench.py serve leg end to end at smoke scale
    (two fresh subprocesses, one rung): run 1 misses and compiles, run 2
    hits — asserted on the children's own instrumentation."""
    import importlib.util
    import shutil

    spec = importlib.util.spec_from_file_location(
        "coldstart_bench", REPO / "tools" / "coldstart_bench.py")
    cb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cb)

    # Own manifest-free checkpoint copy: the module fixture's manifest
    # records a (1, 2) ladder, this smoke leg serves ladder (1,).
    src, _, _ = tiny_ckpt
    ckpt = tmp_path / "ckpt"
    shutil.copytree(src, ckpt)
    for d in (ckpt, ckpt / "final"):  # either manifest spelling
        (d / "warmup.json").unlink(missing_ok=True)
    cold = cb._run_serve_child(ckpt, tmp_path / "cc", buckets="1",
                               num_classes=3, timeout_s=300)
    warm = cb._run_serve_child(ckpt, tmp_path / "cc", buckets="1",
                               num_classes=3, timeout_s=300)
    assert cold["compile_cache"]["hits"] == 0
    assert cold["compile_cache"]["misses"] >= 1
    assert warm["compile_cache"]["hits"] >= 1
    assert warm["compile_cache"]["misses"] == 0
    for leg in (cold, warm):
        assert leg["time_to_all_buckets_warm_s"] > 0
        assert leg["time_to_first_batch_s"] > 0
        assert leg["warmup"]["done"] and leg["warm_rungs"] == [1]


@pytest.mark.slow
def test_coldstart_full_harness(tmp_path):
    """The full train+serve A/B at artifact scale (minutes of fresh
    subprocesses) — the committed evidence path, excluded from tier-1."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "coldstart_bench", REPO / "tools" / "coldstart_bench.py")
    cb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cb)
    result = cb.run_coldstart(workdir=tmp_path)
    assert result["cs_train_cold_s"] > 0 and result["cs_serve_cold_s"] > 0
    assert result["serve"]["warm"]["compile_cache"]["hits"] >= 3


# ------------------------------------------------ bench compact line
def test_compact_gates_line_stays_bounded():
    """The r8 satellite: the final compact line — headline + EVERY gate
    key bench.py can emit (scraped from its source, so a future gate
    can't silently outgrow the bound) + the cs_*/telemetry/bi_*
    extras — fits the driver's tail-capture budget (<=900 chars since
    r18; the capture is 2000, the bound protects >2x headroom)."""
    import importlib.util
    import re

    spec = importlib.util.spec_from_file_location("bench_mod",
                                                  REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    src = (REPO / "bench.py").read_text()
    gate_keys = set(re.findall(r'"([a-z0-9_]+_ok)"', src))
    assert "cold_start_ok" in gate_keys  # the r8 gate rides the line
    assert "telemetry_overhead_ok" in gate_keys  # the r9 gate rides too
    assert "batch_infer_ok" in gate_keys  # the r11 gate rides too
    assert "fleet_serve_ok" in gate_keys  # the r13 gate rides too
    assert "elastic_ok" in gate_keys  # the r14 gate rides too
    assert "multihead_ok" in gate_keys  # the r14 multihead gate too
    assert "search_ok" in gate_keys  # the r15 search gate rides too
    assert "autoscale_ok" in gate_keys  # the r16 autoscale gate too
    assert "deploy_ok" in gate_keys  # the r17 flywheel gate rides too
    assert "cascade_ok" in gate_keys  # the r18 cascade gate rides too
    payload = {"value": 8857.13, "mfu": 0.4693, "tflops": 92.45}
    for k in gate_keys:
        payload[k] = False
    for k in bench.COMPACT_EXTRA_KEYS:
        payload[k] = 8888.888  # worst-case width for the seconds fields
    line = bench.compact_gates_line(payload)
    assert len(line) <= 900
    parsed = json.loads(line)
    assert parsed["cold_start_ok"] is False
    assert parsed["cs_serve_cold_s"] == 8888.888
    assert parsed["telemetry_overhead_pct"] == 8888.888
    assert parsed["bi_vs_train"] == 8888.888
    assert parsed["cascade_speedup"] == 8888.888  # r18 evidence rides too
    assert parsed["cascade_agreement"] == 8888.888

    # r9 satellite: the telemetry subsystem's instrument/row names must
    # never collide with the JSONL vocabulary the repo already emits
    # (engine.train metric rows, ServeStats.emit rows) — a merged
    # stream must stay attributable by key alone. The row spine
    # (time/step/epoch) is deliberately shared.
    from pytorch_vit_paper_replication_tpu.telemetry import (INSTRUMENTS,
                                                             ROW_KEYS)
    existing_jsonl_keys = {
        # engine.train -> MetricsLogger rows
        "time", "step", "epoch", "train_loss", "train_acc", "test_loss",
        "test_acc", "images_per_sec", "grad_norm", "skipped_steps", "lr",
        "time_to_first_step", "compile_cache_hits",
        "compile_cache_misses",
        # ServeStats.emit flattened rows
        "submitted", "completed", "rejected_queue_full", "expired",
        "batches", "padded_rows", "degraded_batches", "warmup_total_s",
        "time_to_first_batch_s",
    } | {f"lat_{leg}_{q}" for leg in ("queue", "device", "total")
         for q in ("p50", "p95", "p99", "count")}
    telemetry_keys = set(INSTRUMENTS) | set(ROW_KEYS)
    shared_spine = {"time", "step", "epoch"}
    collisions = telemetry_keys & (existing_jsonl_keys - shared_spine)
    assert not collisions, (
        f"telemetry names collide with existing JSONL keys: {collisions}")

    # r10 satellite: the Prometheus renderer grew # HELP metadata — the
    # SAMPLE names must stay exactly the r9 ones (dashboards/scrape
    # configs key on them). Render a representative registry and assert
    # the name grammar byte-for-byte.
    from pytorch_vit_paper_replication_tpu.telemetry import (
        TelemetryRegistry)
    reg = TelemetryRegistry()
    reg.count("tel_steps_total", 3)
    reg.set_counter("serve_completed_total", 7)
    reg.gauge("serve_latency_total_p99_s", 0.078)
    for v in (0.1, 0.2, 0.3):
        reg.observe("tel_step_s", v)
    text = reg.to_prometheus()
    stable_samples = (
        "vit_tel_steps_total 3",
        "vit_serve_completed_total 7",
        "vit_serve_latency_total_p99_s 0.078",
        'vit_tel_step_s{quantile="0.5"} 0.2',
        'vit_tel_step_s{quantile="0.95"} ',
        'vit_tel_step_s{quantile="0.99"} ',
        "vit_tel_step_s_count 3",
        "vit_tel_step_s_sum ",
    )
    for sample in stable_samples:
        assert sample in text, f"stable sample name lost: {sample!r}"
    # And every metric now carries HELP + TYPE metadata.
    for name in ("vit_tel_steps_total", "vit_serve_completed_total",
                 "vit_tel_step_s"):
        assert f"# HELP {name} " in text
        assert f"# TYPE {name} " in text


def test_train_cli_logs_time_to_first_step(tmp_path):
    """The run-log field the coldstart bench consumes: a real (tiny)
    train run writes time_to_first_step to its metrics JSONL exactly
    once, on the first epoch record."""
    from pytorch_vit_paper_replication_tpu.train import main as train_main

    jsonl = tmp_path / "m.jsonl"
    train_main([
        "--synthetic", "--preset", "ViT-Ti/16", "--image-size", "32",
        "--patch-size", "16", "--dtype", "float32", "--attention", "xla",
        "--epochs", "2", "--batch-size", "8", "--synthetic-per-class", "4",
        "--num-workers", "1", "--metrics-jsonl", str(jsonl),
        "--compile-cache-dir", str(tmp_path / "cache")])
    records = [json.loads(line) for line in
               jsonl.read_text().splitlines() if line.strip()]
    ttfs = [r for r in records if "time_to_first_step" in r]
    assert len(ttfs) == 1 and ttfs[0]["epoch"] == 1
    assert ttfs[0]["time_to_first_step"] > 0
    # the salted cache dir exists and received entries
    salted = list((tmp_path / "cache").iterdir())
    assert len(salted) == 1
