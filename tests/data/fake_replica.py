"""A jax-free stand-in serve replica for the fleet tests.

Speaks exactly the slice of the serve CLI protocol the fleet layer
touches — the readiness stderr line, the TSV request/response shape,
``::stats`` / ``::drain`` / ``::probs``, and the ISSUE 12 multi-head
forms (``::head`` / ``::tier`` connection state and the inline
``::req head=H tier=T <path>`` the router relays; a non-probs request
answers ``path<TAB><tag>:<head>:<tier><TAB>0.9000`` so tests can
assert which tags actually reached the replica, and a relayed
``model=`` tag (ISSUE 19 cascade tiering) is echoed the same way as
``<tag>:<head>:<tier>:<model>``) — in a few
milliseconds of startup instead of a multi-second jax import, so
router/manager/rollout semantics (re-dispatch on SIGKILL, staleness,
rolling swap, rollback) are testable deterministically in tier-1 time.

Behavior knobs:

* ``--ckpt PATH`` — identity; a path whose basename contains ``bad``
  exits(3) BEFORE listening (the rollout's failed-restart case). The
  ``::probs`` row is a deterministic function of the ckpt string, so a
  test can compute the expected row without talking to the process.
* ``--probs-by-path`` — the ``::probs`` row additionally keys on the
  requested path (a per-image margin spread, so a mid cascade
  threshold splits traffic instead of all-or-nothing).
* ``--warm CSV`` — the warm_rungs the ``::stats`` snapshot reports.
* ``--delay-s S`` — per-request service delay (gives SIGKILL tests a
  mid-request window).
"""

from __future__ import annotations

import argparse
import hashlib
import importlib.util
import json
import socketserver
import sys
import time
from pathlib import Path


def _load_tracing():
    """Load telemetry/tracing.py by FILE PATH (no package import): the
    module is stdlib-only by contract, so the fake replica can strip
    ``trace=`` wire tokens and emit replica-side spans without paying
    the jax import the whole point of this file is to avoid."""
    path = (Path(__file__).resolve().parents[2] /
            "pytorch_vit_paper_replication_tpu" / "telemetry" /
            "tracing.py")
    spec = importlib.util.spec_from_file_location("_fake_tracing", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def probs_for_ckpt(ckpt: str, n: int = 3):
    """Deterministic fake softmax row derived from the ckpt string."""
    digest = hashlib.sha256(ckpt.encode()).digest()
    raw = [1.0 + digest[i] for i in range(n)]
    total = sum(raw)
    return [round(v / total, 6) for v in raw]


def probs_for_path(ckpt: str, path: str, n: int = 3):
    """Per-image variant (``--probs-by-path``): the row depends on the
    requested path too, so cascade tests get a SPREAD of top-1/top-2
    margins across one fleet instead of one constant row per replica —
    a mid threshold then genuinely splits traffic."""
    return probs_for_ckpt(f"{ckpt}\x00{path}", n)


def fingerprint_for_ckpt(ckpt: str) -> str:
    """Deterministic stand-in for the serve engine's checkpoint
    content fingerprint (tests compute the expected value without
    talking to the process)."""
    return hashlib.sha256(ckpt.encode()).hexdigest()[:16]


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt", required=True)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--warm", default="1,8")
    p.add_argument("--delay-s", type=float, default=0.0)
    p.add_argument("--probs-by-path", action="store_true")
    p.add_argument("--trace-jsonl", default=None,
                   help="append span JSONL here (ISSUE 20 tracing)")
    p.add_argument("--trace-role", default="replica")
    args = p.parse_args(argv)

    tracing = _load_tracing()
    tracer = tracing.Tracer(args.trace_jsonl, role=args.trace_role)

    if "bad" in args.ckpt.rsplit("/", 1)[-1]:
        print("[fake] refusing to boot: bad checkpoint",
              file=sys.stderr, flush=True)
        return 3

    warm = [int(b) for b in args.warm.split(",") if b.strip()]
    probs = probs_for_ckpt(args.ckpt)
    tag = args.ckpt.rsplit("/", 1)[-1]
    state = {"completed": 0, "draining": False}

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            conn = {"head": "probs", "tier": "interactive"}
            for raw_line in self.rfile:
                line = raw_line.decode("utf-8", "replace").strip()
                if not line:
                    continue
                # Strip the trace token BEFORE parsing (every hop's
                # ingress contract) so replies stay byte-exact; the
                # span records only when a sink is configured.
                hdr, line = tracing.extract_wire_context(line)
                ctx = tracer.accept(hdr)
                t_req = time.time()
                if line == "::stats":
                    reply = json.dumps({
                        "queue_depth": 0, "warm_rungs": warm,
                        "counters": {"completed": state["completed"]},
                        "checkpoint_fingerprint":
                        fingerprint_for_ckpt(args.ckpt),
                        "ckpt": args.ckpt})
                elif line.startswith("::drain"):
                    state["draining"] = True
                    reply = json.dumps({"draining": True,
                                        "unfinished": 0})
                elif line.startswith("::probs "):
                    if args.delay_s:
                        # same mid-request SIGKILL window as the TSV
                        # path (the cascade failover tests need it)
                        time.sleep(args.delay_s)
                    row = probs
                    if args.probs_by_path:
                        row = probs_for_path(
                            args.ckpt, line[len("::probs "):].strip())
                    state["completed"] += 1
                    reply = json.dumps({
                        "label": "fake", "prob": max(row),
                        "probs": row})
                elif line.startswith("::head ") or \
                        line.startswith("::tier "):
                    key = line[2:6]
                    conn[key] = line.split()[1]
                    reply = f"::{key}\tok\t{conn[key]}"
                elif state["draining"]:
                    reply = (f"{line}\tERROR\tDrainingError: batcher "
                             f"draining (quiesce); retry after ~0.050s")
                else:
                    head, tier = conn["head"], conn["tier"]
                    k = model = None
                    if line.startswith("::req"):
                        # The inline form the router relays: strip the
                        # tags, answer for the bare path.
                        parts = line.split()
                        path_parts = []
                        for part in parts[1:]:
                            if part.startswith("head="):
                                head = part[len("head="):]
                            elif part.startswith("tier="):
                                tier = part[len("tier="):]
                            elif part.startswith("k="):
                                k = part[len("k="):]
                            elif part.startswith("model="):
                                model = part[len("model="):]
                            else:
                                path_parts.append(part)
                        line = " ".join(path_parts)
                    if args.delay_s:
                        time.sleep(args.delay_s)
                    state["completed"] += 1
                    if k is not None:
                        # The ISSUE 13 search slice: echo which k/tier
                        # the relayed ::search actually carried.
                        reply = (f"{line}\tsearch\t"
                                 f'{{"k": {k}, "tag": "{tag}:{tier}"}}')
                    elif model is not None:
                        # ISSUE 19 tag echo: prove which model= tag the
                        # router's hard filter actually relayed here.
                        reply = (f"{line}\t{tag}:{head}:{tier}:{model}"
                                 f"\t0.9000")
                    elif head == "probs":
                        reply = f"{line}\t{tag}\t0.9000"
                    else:
                        # Tag echo: tests assert which head/tier the
                        # relayed request actually carried.
                        reply = f"{line}\t{tag}:{head}:{tier}\t0.9000"
                if ctx is not None and not line.startswith(
                        ("::stats", "::drain", "::head", "::tier")):
                    tracer.record(ctx, "serve.request", t_req,
                                  time.time(), path=line, fake=True)
                self.wfile.write((reply + "\n").encode())
                self.wfile.flush()

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    with Server(("127.0.0.1", args.port), Handler) as srv:
        # The SAME readiness shape the serve CLI prints.
        print(f"[serve] listening on 127.0.0.1:"
              f"{srv.server_address[1]} (fake replica {tag})",
              file=sys.stderr, flush=True)
        srv.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
