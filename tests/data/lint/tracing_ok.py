"""trace-propagate fixture (clean): both legitimate hop shapes — an
INGRESS that strips the trace= token off the line before parsing, and
an INTERIOR hop that accepts the already-extracted context from its
caller — plus a parser call behind a non-serve consumer boundary that
the scope config keeps out of the rule's reach."""


def parse_req_line(line):
    return "probs", "interactive", None, None, line.split()[-1]


def extract_wire_context(line):
    return None, line


def handle_request(line, engine):
    # Ingress shape: token off the wire BEFORE the parse eats it.
    hdr, line = extract_wire_context(line)
    head, tier, _k, _model, path = parse_req_line(line)
    return engine.submit(path, head=head, tier=tier), hdr


class Handler:
    def route_search(self, line, ctx=None):
        # Interior-hop shape: the caller extracted; ctx rides down.
        k, path = self.parse_search_line(line)
        return self.dispatch(path, k=k, ctx=ctx)

    def parse_search_line(self, line):
        parts = line.split()
        return int(parts[1]), parts[2]

    def dispatch(self, path, k, ctx=None):
        return path, k, ctx
