"""vitlint fixture: lock-order FAILING case — a synthetic AB/BA
deadlock: ``A.poke`` holds A's lock while entering B's, ``B.cross``
holds B's lock while entering A's."""

import threading


class A:
    def __init__(self, b=None):
        self._lock = threading.Lock()
        self.b = b if b is not None else B()

    def poke(self):
        with self._lock:
            self.b.tick()         # A._lock -> B._lock


class B:
    def __init__(self, a=None):
        self._lock = threading.Lock()
        self.a = a if a is not None else A()

    def tick(self):
        with self._lock:
            pass

    def cross(self):
        with self._lock:
            self.a.poke()         # B._lock -> A._lock: cycle
