"""vitlint fixture: dead-flag/shadowed-flag FAILING case — one flag
parsed but never consumed, one dest registered twice."""

import argparse


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--used", type=int, default=0)
    p.add_argument("--never-read", type=int, default=0)   # dead
    p.add_argument("--also-used", dest="used", type=int)  # shadowed
    args = p.parse_args()
    return args.used
