"""vitlint fixture: atomic-manifest PASSING case — the inline
temp + ``os.replace`` pattern (what ``utils.atomic`` wraps)."""

import json
import os


def save_progress(out_dir, payload):
    path = out_dir / "progress.json"
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(payload))
    os.replace(tmp, path)
