"""vitlint fixture: hot-path-sync FAILING case (deliberate violations).

A per-step loop with a blocking device->host conversion, a host
barrier, host I/O, and a sync hidden one call away in a same-module
helper (exercises the call-following closure).
"""

import jax
import numpy as np


def _hidden_drain(y):
    return np.asarray(y)          # reached via the step loop's call


def step_loop(batches, step, state):
    for batch in batches:
        state, metrics = step(state, batch)
        loss = np.asarray(metrics["loss"])        # banned: numpy sync
        jax.block_until_ready(metrics)            # banned: barrier
        print("loss", loss)                       # banned: host I/O
        _hidden_drain(metrics)                    # banned via helper
    return state
