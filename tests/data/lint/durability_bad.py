"""vitlint fixture: atomic-manifest FAILING case — a progress manifest
written with a plain ``write_text`` (torn on SIGKILL mid-write)."""

import json


def save_progress(out_dir, payload):
    (out_dir / "progress.json").write_text(json.dumps(payload))
