"""vitlint fixture: instrument-declared PASSING case — a declared
literal and a dynamic name riding a declared namespace prefix."""


def publish(reg, leg):
    reg.count("tel_steps_total")           # declared in INSTRUMENTS
    reg.observe(f"serve_lat_{leg}_s", 0.1)  # declared serve_ namespace
