"""trace-propagate fixture: a serve-layer wire-protocol hop that DROPS
the request's trace context — it parses the request grammar but never
strips the trace= token (extract_wire_context) and takes no ``ctx``
parameter, so a traced request's causal chain dies here silently."""


def parse_req_line(line):
    return "probs", "interactive", None, None, line.split()[-1]


def handle_request(line, engine):
    head, tier, _k, _model, path = parse_req_line(line)
    return engine.submit(path, head=head, tier=tier)


class Handler:
    def route_search(self, line):
        # Same drop through the search grammar, attribute-call shape.
        k, path = self.parse_search_line(line)
        return self.dispatch(path, k=k)

    def parse_search_line(self, line):
        parts = line.split()
        return int(parts[1]), parts[2]

    def dispatch(self, path, k):
        return path, k
