"""vitlint fixture: lock-discipline PASSING case.

Every shared-state mutation is guarded — lexically, or in a private
held-context method whose only call sites hold the lock (the
``MicroBatcher._collect`` pattern). ``_hits`` is single-writer state
never touched under the lock, so it is NOT inferred as lock-owned.
"""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._items = []
        self._hits = 0            # single-writer, never lock-guarded

    def add(self, v):
        with self._lock:
            self._n += v
            self._bump(v)

    def _bump(self, v):
        # caller holds the lock (held-context private method)
        self._items.append(v)

    def touch(self):
        self._hits += 1           # fine: not lock-owned state
