"""vitlint fixture: signal-safety PASSING case — the handler path uses
a reentrant RLock (same-thread reentry can't deadlock; the Watchdog
postmortem contract)."""

import signal
import threading


class Dumper:
    def __init__(self):
        self._lock = threading.RLock()
        self.n = 0

    def install(self):
        self._handler = self._on_term
        signal.signal(signal.SIGTERM, self._handler)

    def _on_term(self, signum, frame):
        self.dump()

    def dump(self):
        with self._lock:          # RLock: reentrant, handler-safe
            return self.n
