"""vitlint fixture: signal-safety FAILING case — the SIGTERM handler
reaches a blocking ``with`` on a plain (non-reentrant) Lock: a signal
landing while THIS thread holds the lock deadlocks the handler."""

import signal
import threading


class Dumper:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def install(self):
        self._handler = self._on_term
        signal.signal(signal.SIGTERM, self._handler)

    def _on_term(self, signum, frame):
        self.dump()

    def dump(self):
        with self._lock:          # plain Lock in the signal path
            return self.n
