"""vitlint fixture: dead-flag PASSING case — every dest consumed,
including the sys.argv-sniffed pattern (`--cpu` read by literal before
jax import, registered only so argparse accepts it)."""

import argparse
import sys

if "--cpu" in sys.argv:
    BACKEND = "cpu"


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--used", type=int, default=0)
    p.add_argument("--also", type=int, default=0)
    p.add_argument("--cpu", action="store_true",
                   help="consumed via the sys.argv sniff above")
    args = p.parse_args()
    return args.used + args.also
