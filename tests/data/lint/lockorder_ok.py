"""vitlint fixture: lock-order PASSING case — nesting in ONE global
order (A before B, never the reverse) is deadlock-free."""

import threading


class B:
    def __init__(self):
        self._lock = threading.Lock()

    def tick(self):
        with self._lock:
            pass


class A:
    def __init__(self):
        self._lock = threading.Lock()
        self.b = B()

    def poke(self):
        with self._lock:
            self.b.tick()         # A._lock -> B._lock only
