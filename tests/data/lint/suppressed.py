"""vitlint fixture: suppression parsing — the violation from
durability_bad, silenced by an inline budgeted suppression."""

import json


def save_progress(out_dir, payload):
    # vitlint: disable=atomic-manifest(fixture: testing suppression parsing)
    (out_dir / "progress.json").write_text(json.dumps(payload))
