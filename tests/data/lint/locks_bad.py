"""vitlint fixture: lock-discipline FAILING case.

``_n``/``_items`` are mutated under the lock in ``add`` — that makes
them lock-owned shared state — and then mutated WITHOUT the lock in
``sneak``.
"""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._items = []

    def add(self, v):
        with self._lock:
            self._n += v
            self._items.append(v)

    def sneak(self, v):
        self._n += v              # unlocked shared-state mutation
        self._items.append(v)     # unlocked shared-state mutation
