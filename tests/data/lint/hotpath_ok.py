"""vitlint fixture: hot-path-sync PASSING case.

The same loop shape kept clean: async dispatch (``jnp.asarray``) plus
one deliberate, annotated drain — the contract's escape hatch.
"""

import jax.numpy as jnp
import numpy as np


def step_loop(batches, step, state):
    last = None
    for batch in batches:
        state, metrics = step(state, jnp.asarray(batch))  # async: fine
        # vitlint: hot-path-ok(fixture: deliberate annotated drain)
        last = np.asarray(metrics["loss"])
    return state, last
