"""vitlint fixture: signal-read-declared PASSING case — declared
literal names, a declared-namespace dynamic name, and a same-named
call on a non-reader receiver that must not fire."""


def read_gauge(snap, name, default=0.0):
    return snap.get("gauges", {}).get(name, default)


def read_p99(snap, name):
    return (snap.get("histograms", {}).get(name) or {}).get("p99")


def decide(snap, rid):
    lat = read_gauge(snap, "fleet_route_lat_ema_s")
    p99 = read_p99(snap, "fleet_route_lat_s")
    up = read_gauge(snap, f"replica_up_{rid}")   # declared namespace
    return lat, p99, up
