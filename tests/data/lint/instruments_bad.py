"""vitlint fixture: instrument-declared FAILING case — an undeclared
literal instrument name and a dynamic name on no declared prefix."""


def publish(reg, idx):
    reg.count("bogus_metric_total")        # not in INSTRUMENTS
    reg.gauge(f"zzz_{idx}_bytes", 1)       # undeclared namespace
