"""vitlint fixture: signal-read-declared FAILING case — a control
loop reading an instrument nobody registers (renamed gauge drift) and
a dynamic read on no declared namespace."""


def read_gauge(snap, name, default=0.0):
    return snap.get("gauges", {}).get(name, default)


def read_p99(snap, name):
    return (snap.get("histograms", {}).get(name) or {}).get("p99")


def decide(snap, idx):
    # The fleet publishes fleet_route_lat_ema_s; this read drifted.
    lat = read_gauge(snap, "fleet_route_latency_ema_s")
    # Undeclared namespace: nothing can be publishing zzz_*.
    depth = read_p99(snap, f"zzz_{idx}_depth_s")
    return lat, depth
