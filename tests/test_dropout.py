"""uint8-threshold dropout (ops/dropout.py): quantization, bias, API."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_vit_paper_replication_tpu.ops.dropout import (
    Dropout, dropout, quantized_rate)


def test_quantized_rate_values():
    assert quantized_rate(0.0) == 0.0
    assert quantized_rate(0.5) == 0.5
    assert quantized_rate(0.1) == pytest.approx(26 / 256)
    # quantization error is bounded by 1/512
    for r in (0.03, 0.1, 0.25, 0.77):
        assert abs(quantized_rate(r) - r) <= 1 / 512


@pytest.mark.parametrize("bad", [-0.1, 1.5])
def test_quantized_rate_rejects_out_of_range(bad):
    with pytest.raises(ValueError):
        quantized_rate(bad)


def test_sub_quantum_rate_warns_and_is_identity():
    """ADVICE r2: a nonzero rate that rounds to threshold 0 must not be a
    SILENT no-op — it warns, and the output is the identity."""
    x = jnp.ones((4, 4))
    with pytest.warns(UserWarning, match="quantizes to 0"):
        out = dropout(x, 0.001, jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    with pytest.warns(UserWarning, match="quantizes to 0"):
        assert quantized_rate(1e-4) == 0.0


def test_dropout_rate_one_drops_everything():
    """flax.linen.Dropout parity at the rate=1.0 edge."""
    x = jnp.ones((8, 8))
    out = np.asarray(dropout(x, 1.0, jax.random.key(0)))
    np.testing.assert_array_equal(out, 0.0)


def test_dropout_near_one_clamps_to_255():
    """Rates just under 1 clamp to 255/256 instead of overflowing uint8."""
    assert quantized_rate(0.999) == pytest.approx(255 / 256)
    x = jnp.ones((256, 256))
    out = np.asarray(dropout(x, 0.999, jax.random.key(0)))
    assert 0.0 < (out == 0.0).mean() < 1.0  # drops most, not all


def test_dropout_rate_zero_is_identity():
    x = jnp.arange(12.0).reshape(3, 4)
    out = dropout(x, 0.0, jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    # rates that quantize to zero are also identity (and warn — see
    # test_sub_quantum_rate_warns_and_is_identity)
    with pytest.warns(UserWarning):
        out = dropout(x, 1e-4, jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_dropout_drop_fraction_matches_quantized_rate():
    rate = 0.1
    x = jnp.ones((512, 512))
    out = np.asarray(dropout(x, rate, jax.random.key(1)))
    dropped = (out == 0.0).mean()
    assert abs(dropped - quantized_rate(rate)) < 5e-3


def test_dropout_is_unbiased():
    """Survivor scaling uses the quantized rate, so E[out] == E[in]."""
    rate = 0.1
    x = jnp.ones((1024, 1024))
    out = np.asarray(dropout(x, rate, jax.random.key(2)))
    # survivors are scaled by exactly 1/(1 - 26/256)
    survivors = out[out != 0.0]
    np.testing.assert_allclose(survivors, 1.0 / (1.0 - 26 / 256), rtol=1e-6)
    assert abs(out.mean() - 1.0) < 2e-3


def test_dropout_preserves_dtype():
    for dt in (jnp.float32, jnp.bfloat16):
        x = jnp.ones((8, 8), dt)
        assert dropout(x, 0.5, jax.random.key(3)).dtype == dt


def test_dropout_module_flax_compatible():
    """Module merges `deterministic` like flax.linen.Dropout and draws from
    the 'dropout' collection."""
    x = jnp.ones((64, 64))
    mod = Dropout(rate=0.5)
    det = mod.apply({}, x, deterministic=True)
    np.testing.assert_array_equal(np.asarray(det), np.asarray(x))

    a = mod.apply({}, x, deterministic=False,
                  rngs={"dropout": jax.random.key(4)})
    b = mod.apply({}, x, deterministic=False,
                  rngs={"dropout": jax.random.key(5)})
    assert not np.allclose(np.asarray(a), np.asarray(b))
    assert (np.asarray(a) == 0).any()


def test_dropout_gradient_matches_mask():
    """d/dx flows only through survivors, scaled like the forward."""
    x = jnp.ones((128,))
    rng = jax.random.key(6)
    g = jax.grad(lambda x: dropout(x, 0.5, rng).sum())(x)
    out = dropout(x, 0.5, rng)
    np.testing.assert_allclose(np.asarray(g), np.asarray(out), rtol=1e-6)
