"""Utility-layer tests: seeding, model summary (torchinfo analog), loss
curves, metrics logger, throughput timer, LR schedule integration."""

import json

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_vit_paper_replication_tpu.metrics import MetricsLogger, Timer
from pytorch_vit_paper_replication_tpu.models import ViT
from pytorch_vit_paper_replication_tpu.utils import (
    count_params, plot_loss_curves, set_seeds, summarize)
from pytorch_vit_paper_replication_tpu.utils.model_summary import (
    format_size, param_bytes)


def test_set_seeds_reproducible():
    k1 = set_seeds(123)
    a = np.random.rand(3)
    k2 = set_seeds(123)
    b = np.random.rand(3)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(jax.random.key_data(k1),
                                  jax.random.key_data(k2))


def test_count_params_and_bytes(tiny_config):
    model = ViT(tiny_config)
    params = jax.eval_shape(lambda: model.init(
        jax.random.key(0),
        jnp.zeros((1, tiny_config.image_size, tiny_config.image_size, 3))
    ))["params"]
    n = count_params(params)
    assert n > 0
    assert param_bytes(params) == n * 4  # float32 params
    assert f"{n:,}" in format_size(params)


def test_summarize_contains_layers(tiny_config):
    model = ViT(tiny_config)
    table = summarize(
        model, jnp.zeros((1, tiny_config.image_size,
                          tiny_config.image_size, 3)))
    assert "backbone" in table
    assert "head" in table


def test_metrics_logger_jsonl(tmp_path):
    path = tmp_path / "m.jsonl"
    logger = MetricsLogger(path)
    logger.log(step=1, loss=0.5)
    logger.log(step=2, loss=jnp.asarray(0.25))  # device scalars coerced
    logger.close()
    records = [json.loads(line) for line in path.read_text().splitlines()]
    assert records[0]["step"] == 1
    assert records[1]["loss"] == 0.25
    assert "time" in records[0]


def test_timer_throughput():
    import time

    t = Timer()
    t.start()
    t.tick(32)
    t.tick(32)
    time.sleep(0.1)  # make elapsed large vs the gap between property reads
    ips = t.images_per_sec
    assert 0 < ips < 64 / 0.1 * 1.5
    # elapsed keeps ticking between property reads; compare with tolerance.
    assert abs(t.images_per_sec_per_chip(n_chips=2) - ips / 2) < ips * 0.05


def test_plot_loss_curves_saves(tmp_path):
    results = {"train_loss": [1.0, 0.5], "test_loss": [1.1, 0.6],
               "train_acc": [0.5, 0.8], "test_acc": [0.4, 0.7]}
    out = tmp_path / "curves.png"
    fig = plot_loss_curves(results, save_path=out)
    if fig is not None:  # matplotlib present
        assert out.exists() and out.stat().st_size > 0


def test_metrics_logger_tensorboard(tmp_path):
    """The TensorBoard claim in metrics.py is real: scalars land in an
    event file."""
    from pytorch_vit_paper_replication_tpu.metrics import MetricsLogger

    logger = MetricsLogger(tb_dir=tmp_path / "tb")
    logger.log(step=1, train_loss=0.5, train_acc=0.9, note="skipme")
    logger.log(step=2, train_loss=0.25, train_acc=0.95)
    logger.close()
    events = list((tmp_path / "tb").glob("events.out.tfevents.*"))
    assert events and events[0].stat().st_size > 0
