"""Serving-fleet tests (ISSUE 10): device partitioning, routing
policies, the router's exactly-once re-dispatch under replica SIGKILL,
health-gated membership + supervised restart, the rolling checkpoint
hot-swap with rollback, phase-tagged bench windows, and one REAL
serve-CLI replica behind the router proving cross-process bit-identity.

Most process tests ride ``tests/data/fake_replica.py`` — a jax-free
stand-in speaking the exact protocol slice the fleet layer touches —
so supervision semantics run in tier-1 time; the real-replica test and
``tools/fleet_bench.py`` (bench gate + committed run) cover the true
serve CLI.
"""

import importlib.util
import json
import os
import signal
import socket
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from pytorch_vit_paper_replication_tpu.serve.fleet import (
    FleetRouter, LeastLoadedAffinity, ReplicaManager, ReplicaSpec,
    ReplicaView, RoundRobin, build_serve_command, is_backpressure,
    make_policy, partition_devices, replica_env, rolling_swap)
from pytorch_vit_paper_replication_tpu.telemetry.registry import (
    HELP_TEXT, INSTRUMENTS, TelemetryRegistry)

REPO = Path(__file__).resolve().parent.parent
FAKE = REPO / "tests" / "data" / "fake_replica.py"


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "tools" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_fake_module():
    spec = importlib.util.spec_from_file_location("fake_replica", FAKE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------- partitioning
def test_partition_devices_even_and_wrapped():
    assert partition_devices(8, 2) == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert partition_devices(8, 3) == [[0, 1, 2], [3, 4, 5], [6, 7]]
    assert partition_devices(2, 4) == [[0], [1], [0], [1]]
    assert partition_devices(1, 1) == [[0]]
    with pytest.raises(ValueError):
        partition_devices(0, 1)
    with pytest.raises(ValueError):
        partition_devices(4, 0)


def test_replica_env_exports_partition():
    env = replica_env([2, 3], base={"KEEP": "1"})
    assert env["KEEP"] == "1"
    assert env["TPU_VISIBLE_DEVICES"] == "2,3"
    assert env["TPU_VISIBLE_CHIPS"] == "2,3"
    assert env["VIT_REPLICA_DEVICES"] == "2,3"


# ------------------------------------------------------------ policy
def _view(rid, *, up=True, draining=False, inflight=0, queue=0,
          warm=(1, 8), addr=("127.0.0.1", 1)):
    return ReplicaView(rid=rid, address=addr, up=up, draining=draining,
                       inflight=inflight, queue_depth=queue,
                       warm_rungs=tuple(warm), restarts=0)


def test_affinity_prefers_warm_rung_then_least_loaded():
    pol = LeastLoadedAffinity()
    views = [_view("r0", warm=(1,), inflight=0),
             _view("r1", warm=(8,), inflight=5)]
    # Affinity wins over load: r1 is busier but warm for rung 8.
    assert pol.choose(views, rung=8) == "r1"
    # No rung hint: pure least-loaded.
    assert pol.choose(views) == "r0"
    # Nobody warm for the rung: least-loaded fallback, not a refusal.
    assert pol.choose(views, rung=32) == "r0"
    # Load ties break by rid (deterministic).
    tied = [_view("rb"), _view("ra")]
    assert pol.choose(tied) == "ra"


def test_policy_filters_down_draining_excluded():
    pol = LeastLoadedAffinity()
    views = [_view("r0", up=False), _view("r1", draining=True),
             _view("r2", addr=None), _view("r3", inflight=9)]
    assert pol.choose(views) == "r3"
    assert pol.choose(views, exclude=frozenset({"r3"})) is None
    assert pol.choose([]) is None


def test_round_robin_cycles():
    pol = RoundRobin()
    views = [_view("r0"), _view("r1")]
    picks = [pol.choose(views) for _ in range(4)]
    assert picks == ["r0", "r1", "r0", "r1"]


def test_make_policy_names():
    assert make_policy("affinity").name == "affinity"
    assert make_policy("round-robin").name == "round-robin"
    with pytest.raises(ValueError, match="unknown routing policy"):
        make_policy("nope")


def test_fleet_instruments_declared_with_help():
    """Every fleet_route_*/fleet_swap_*/replica_* instrument the
    subsystem publishes is declared with HELP_TEXT (vitlint's
    instrument rules enforce the publish sites; this pins the names)."""
    for name in ("fleet_route_requests_total", "fleet_route_retries_total",
                 "fleet_route_rejected_total", "fleet_route_errors_total",
                 "fleet_route_inflight", "fleet_route_lat_s",
                 "fleet_replicas_up", "fleet_swaps_total",
                 "fleet_swap_failures_total",
                 "fleet_swap_rollbacks_total", "fleet_swap_active",
                 "fleet_swap_last_s", "replica_restarts_total"):
        assert name in INSTRUMENTS, name
        assert name in HELP_TEXT, name


# ----------------------------------------------------- phase windows
def test_phase_report_splits_on_marks():
    sb = _load_tool("serve_bench")
    marks = sb.parse_marks(["3=during", "8=post"])
    assert marks == [(3.0, "during"), (8.0, "post")]
    samples = [(1.0, 0.010, True), (4.0, 0.050, True),
               (4.5, 0.2, False), (9.0, 0.020, True)]
    rep = sb.phase_report(samples, marks, first_label="pre")
    assert list(rep) == ["pre", "during", "post"]
    assert rep["pre"]["count"] == 1 and rep["pre"]["p99_ms"] == 10.0
    assert rep["during"]["count"] == 1 and rep["during"]["errors"] == 1
    assert rep["during"]["p99_ms"] == 50.0   # errors never pollute p99
    assert rep["post"]["p50_ms"] == 20.0
    empty = sb.phase_report([], marks, first_label="pre")
    assert empty["pre"]["p99_ms"] is None
    with pytest.raises(ValueError):
        sb.parse_marks(["nolabel"])


def test_serve_bench_open_loop_carries_phases():
    """An open-loop serve_bench run with marks reports per-phase
    percentiles (the --mark satellite, engine-level)."""
    sb = _load_tool("serve_bench")
    engine = sb.make_engine("ViT-Ti/16", 32, 3, (1, 4), 1000, 256)
    try:
        out = sb.run_open_loop(engine, rate_rps=40.0, duration_s=1.2,
                               timeout_s=10.0,
                               marks=[(0.6, "late")])
    finally:
        engine.close()
    assert set(out["phases"]) == {"start", "late"}
    assert (out["phases"]["start"]["count"]
            + out["phases"]["late"]["count"]) == out["completed"]


# ------------------------------------------------------ fake fleet
def _fake_factory(warm_by_rid=None, delay_s=0.0):
    def factory(spec):
        cmd = [sys.executable, str(FAKE), "--ckpt", spec.checkpoint]
        warm = (warm_by_rid or {}).get(spec.rid)
        if warm:
            cmd += ["--warm", warm]
        if delay_s:
            cmd += ["--delay-s", str(delay_s)]
        return cmd
    return factory


def _mk_fleet(tmp_path, *, warm_by_rid=None, delay_s=0.0, n=2,
              ckpt="ckA", auto_restart=True, expected_rungs=None,
              max_retries=2, max_inflight=1024):
    registry = TelemetryRegistry()
    specs = [ReplicaSpec(rid=f"r{i}", checkpoint=str(tmp_path / ckpt))
             for i in range(n)]
    manager = ReplicaManager(
        specs, command_factory=_fake_factory(warm_by_rid, delay_s),
        env_factory=lambda spec: dict(os.environ),
        health_interval_s=0.05, stale_after_s=1.0,
        restart_backoff_s=(0.1, 0.5), auto_restart=auto_restart,
        expected_rungs=expected_rungs, registry=registry)
    router = FleetRouter(manager, registry=registry,
                         max_retries=max_retries,
                         max_inflight=max_inflight,
                         request_timeout_s=30.0)
    return manager, router, registry


def _ask(address, lines, timeout=30.0):
    """Open one connection, send the lines, read one reply each."""
    with socket.create_connection(address, timeout=timeout) as sock:
        sock.settimeout(timeout)
        rfile = sock.makefile("r", encoding="utf-8")
        replies = []
        for line in lines:
            sock.sendall((line + "\n").encode())
            replies.append(rfile.readline().rstrip("\n"))
        rfile.close()
        return replies


def _ask_block(address, line, timeout=30.0):
    """One command whose reply is a blank-line-framed multi-line block
    (::metrics)."""
    with socket.create_connection(address, timeout=timeout) as sock:
        sock.settimeout(timeout)
        rfile = sock.makefile("r", encoding="utf-8")
        sock.sendall((line + "\n").encode())
        lines = []
        for reply in rfile:
            if reply == "\n":
                break
            lines.append(reply)
        rfile.close()
        return "".join(lines)


def test_router_routes_and_answers_stats_metrics(tmp_path):
    manager, router, registry = _mk_fleet(tmp_path)
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        (reply,) = _ask(router.address, ["img1.jpg"])
        path, tag, prob = reply.split("\t")
        assert path == "img1.jpg" and tag == "ckA"
        assert float(prob) == pytest.approx(0.9)
        (stats,) = _ask(router.address, ["::stats"])
        snap = json.loads(stats)
        assert snap["policy"] == "affinity"
        assert set(snap["replicas"]) == {"r0", "r1"}
        assert all(r["up"] for r in snap["replicas"].values())
        assert snap["counters"]["fleet_route_requests_total"] >= 1
        metrics = _ask_block(router.address, "::metrics")
        assert "# TYPE vit_fleet_route_requests_total counter" in metrics
        assert "vit_fleet_replicas_up 2" in metrics
        assert "vit_replica_up_r0 1" in metrics


def test_router_rung_affinity_steers_to_warm_replica(tmp_path):
    manager, router, _ = _mk_fleet(
        tmp_path, warm_by_rid={"r0": "1", "r1": "8"})
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        replies = _ask(router.address,
                       ["::rung 8"] + ["x.jpg"] * 4)
        assert replies[0] == "::rung\tok\t8"
        # Every request from this rung-8 connection rode r1 — but the
        # fake's tag is the ckpt basename (same for both), so assert
        # through the replicas' own served counters instead.
        s0 = json.loads(manager.request("r0", "::stats"))
        s1 = json.loads(manager.request("r1", "::stats"))
        assert s1["counters"]["completed"] == 4
        assert s0["counters"]["completed"] == 0


def test_router_head_tier_relay_stateless(tmp_path):
    """ISSUE 12: ::head/::tier are CLIENT-connection state at the
    router; non-default traffic relays as the inline ::req form (the
    pooled replica connections are shared, so replica-side state can
    never be trusted), the reply echoes the bare path, and the fake
    replica's tag echo proves which head/tier actually arrived."""
    manager, router, _ = _mk_fleet(tmp_path, n=1)
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        replies = _ask(router.address, [
            "::head features", "::tier batch", "img1.jpg",
            "::head probs", "::tier interactive", "img2.jpg",
            "::req head=tokens img3.jpg",
            "::head logits",
        ])
        assert replies[0] == "::head\tok\tfeatures"
        assert replies[1] == "::tier\tok\tbatch"
        path, tag, _prob = replies[2].split("\t")
        assert path == "img1.jpg" and tag == "ckA:features:batch"
        # Back to defaults: the relayed line is the BARE path again
        # (byte-identical to the pre-multi-head protocol).
        assert replies[3] == "::head\tok\tprobs"
        assert replies[4] == "::tier\tok\tinteractive"
        assert replies[5].split("\t")[1] == "ckA"
        # One-shot ::req: overrides without touching connection state.
        path, tag, _prob = replies[6].split("\t")
        assert path == "img3.jpg" and tag == "ckA:tokens:interactive"
        assert "\tERROR\tValueError" in replies[7]   # bad head value


def test_router_refuses_unknown_control_commands(tmp_path):
    """Control lines are router-owned: ::drain must NOT relay to a
    replica (any client could permanently quiesce it through the
    front door) — it answers an error, and the replicas never see it."""
    manager, router, _ = _mk_fleet(tmp_path)
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        (reply,) = _ask(router.address, ["::drain 5"])
        assert "\tERROR\t" in reply and "unknown" in reply
        # The replicas still admit traffic (nothing was drained).
        (ok,) = _ask(router.address, ["still.jpg"])
        assert "\tERROR\t" not in ok


def test_router_admission_bounds_inflight(tmp_path):
    manager, router, registry = _mk_fleet(tmp_path, max_inflight=0)
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        (reply,) = _ask(router.address, ["x.jpg"])
        assert "\tERROR\tQueueFullError" in reply
        assert "retry after" in reply
        assert is_backpressure(reply)
        counters = registry.snapshot()["counters"]
        assert counters["fleet_route_rejected_total"] == 1


def test_router_no_replica_available_is_explicit_backpressure(tmp_path):
    manager, router, registry = _mk_fleet(
        tmp_path, ckpt="ckbad", auto_restart=False)
    with manager, router:
        manager.start()   # fakes exit(3) before listening
        router.start()
        time.sleep(0.3)
        (reply,) = _ask(router.address, ["x.jpg"])
        assert "\tERROR\tNoReplicaAvailable" in reply
        assert "retry after" in reply
        counters = registry.snapshot()["counters"]
        assert counters["fleet_route_errors_total"] == 1


def test_replica_sigkill_mid_load_redispatch_exactly_once(tmp_path):
    """THE replica-death satellite: SIGKILL a replica under live load;
    every request is answered exactly once (the router re-dispatches
    the failed ones to the survivor), the dead replica goes down
    within stale_after_s, and the supervised restart re-admits it."""
    manager, router, registry = _mk_fleet(
        tmp_path, warm_by_rid={"r0": "1", "r1": "8"}, delay_s=0.25)
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()

        n_clients = 12
        replies: list = [None] * n_clients
        barrier = threading.Barrier(n_clients + 1)

        def client(i):
            barrier.wait(timeout=20)
            # No rung hint: least-loaded spreads load over BOTH
            # replicas, so some requests are mid-flight on the victim.
            (replies[i],) = _ask(router.address, [f"img{i}.jpg"],
                                 timeout=60.0)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        barrier.wait(timeout=20)
        time.sleep(0.1)   # let requests land on both replicas
        victim_pid = manager.pid_of("r1")
        down_at = [None]
        watch_stop = threading.Event()

        def watch_down():
            while not watch_stop.is_set():
                if not manager.view("r1").up:
                    down_at[0] = time.monotonic()
                    return
                time.sleep(0.01)

        # The supervised restart re-admits r1 within ~100 ms, so the
        # down transition must be observed CONCURRENTLY, not after the
        # load joins.
        watcher = threading.Thread(target=watch_down, daemon=True)
        watcher.start()
        t_kill = time.monotonic()
        os.kill(victim_pid, signal.SIGKILL)
        for t in threads:
            t.join(90)

        # Exactly once: every client got exactly one non-error reply.
        assert all(r is not None for r in replies)
        assert all("\tERROR\t" not in r for r in replies), replies
        counters = registry.snapshot()["counters"]
        assert counters["fleet_route_requests_total"] == n_clients
        assert counters.get("fleet_route_retries_total", 0) >= 1

        # Down within stale_after_s of the kill (process death is
        # detected by poll(), faster than the staleness deadline).
        watcher.join(manager.stale_after_s + 2.0)
        watch_stop.set()
        assert down_at[0] is not None
        assert down_at[0] <= t_kill + manager.stale_after_s

        # Supervised restart re-admits it...
        assert manager.wait_healthy("r1", 20.0)
        assert counters_after_restart(registry) >= 1
        # ...and rung-8 traffic steers to it again (it is routable,
        # not just alive).
        before = json.loads(
            manager.request("r1", "::stats"))["counters"]["completed"]
        _ask(router.address, ["::rung 8", "again.jpg"])
        after = json.loads(
            manager.request("r1", "::stats"))["counters"]["completed"]
        assert after == before + 1


def counters_after_restart(registry) -> int:
    return registry.snapshot()["counters"].get(
        "replica_restarts_total", 0)


def test_rolling_swap_fakes_zero_downtime(tmp_path):
    """Rolling swap over fakes: replicas move to the new checkpoint
    one at a time (never both unroutable), requests keep being
    answered throughout, ::probs flips to the new checkpoint's row."""
    fake = _load_fake_module()
    manager, router, registry = _mk_fleet(
        tmp_path, warm_by_rid={"r0": "1,8", "r1": "1,8"},
        expected_rungs=(1, 8))
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()

        stop = threading.Event()
        errors: list = []
        answered = [0]
        overlap = [0]

        def background_load():
            while not stop.is_set():
                (r,) = _ask(router.address, ["bg.jpg"], timeout=30.0)
                answered[0] += 1
                if "\tERROR\t" in r:
                    errors.append(r)
                time.sleep(0.01)

        def watch_membership():
            while not stop.is_set():
                views = manager.views()
                if sum(1 for v in views if not v.routable) > 1:
                    overlap[0] += 1
                time.sleep(0.01)

        lt = threading.Thread(target=background_load, daemon=True)
        wt = threading.Thread(target=watch_membership, daemon=True)
        lt.start()
        wt.start()
        new_ckpt = str(tmp_path / "ckB")
        expect = np.asarray(fake.probs_for_ckpt(new_ckpt), np.float32)
        report = rolling_swap(
            manager, router, new_ckpt, drain_timeout_s=5.0,
            warm_timeout_s=20.0, probe="probe.jpg",
            expect_probs=expect, registry=registry)
        stop.set()
        lt.join(30)
        wt.join(30)

        assert report["ok"] and not report["rolled_back"]
        assert report["swapped"] == ["r0", "r1"]
        assert all(r["probe"]["matched"]
                   for r in report["replicas"])
        assert not errors and answered[0] > 0
        assert overlap[0] == 0   # never more than one replica out
        counters = registry.snapshot()["counters"]
        assert counters["fleet_swaps_total"] == 1
        # The swap is visible on the router protocol too.
        (status,) = _ask(router.address, ["::swap-status"])
        assert json.loads(status)["ok"] is True
        # And membership stayed healthy: both replicas now report the
        # new checkpoint.
        for rid in ("r0", "r1"):
            snap = json.loads(manager.request(rid, "::stats"))
            assert snap["ckpt"] == new_ckpt


def test_rolling_swap_rolls_back_on_bad_checkpoint(tmp_path):
    """A checkpoint whose replica never comes up triggers rollback:
    the failed replica restarts onto its OLD checkpoint, the fleet
    converges back to fully-up, and the report says so."""
    manager, router, registry = _mk_fleet(
        tmp_path, warm_by_rid={"r0": "1,8", "r1": "1,8"},
        expected_rungs=(1, 8))
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        old = manager.checkpoint_of("r0")
        report = rolling_swap(
            manager, router, str(tmp_path / "ckbad"),
            drain_timeout_s=2.0, warm_timeout_s=2.5,
            registry=registry)
        assert not report["ok"] and report["rolled_back"]
        assert report["swapped"] == []
        assert report["restores"] and all(
            r["healthy"] for r in report["restores"])
        counters = registry.snapshot()["counters"]
        assert counters["fleet_swap_failures_total"] == 1
        assert counters["fleet_swap_rollbacks_total"] == 1
        assert manager.wait_ready(20.0)
        for rid in ("r0", "r1"):
            assert manager.checkpoint_of(rid) == old
            assert not manager.view(rid).draining
        (reply,) = _ask(router.address, ["still.jpg"])
        assert "\tERROR\t" not in reply


def test_rollback_readmits_even_when_restore_is_unhealthy(tmp_path):
    """A rollback whose restore ALSO misses the warm gate must still
    clear `draining` — otherwise a replica the supervisor later heals
    stays silently unroutable forever (review finding)."""
    # expected_rungs demands rung 8 the fakes never report, so every
    # wait_healthy gate fails: the first swap fails, and the restore
    # comes back "unhealthy" too.
    manager, router, registry = _mk_fleet(
        tmp_path, warm_by_rid={"r0": "1", "r1": "1"},
        expected_rungs=(1, 8))
    with manager, router:
        manager.start()
        assert manager.wait_ready(20.0)
        router.start()
        report = rolling_swap(
            manager, router, str(tmp_path / "ckB"),
            drain_timeout_s=1.0, warm_timeout_s=1.5,
            registry=registry)
        assert not report["ok"] and report["rolled_back"]
        assert report["restores"] and not report["restores"][0]["healthy"]
        # The deliberate exclusion is lifted even though the restore
        # missed the gate: up-ness alone governs routability now.
        for rid in ("r0", "r1"):
            assert not manager.view(rid).draining
        (reply,) = _ask(router.address, ["alive.jpg"])
        assert "\tERROR\t" not in reply


def test_router_ships_frames_as_role_router(tmp_path):
    """Router telemetry frames merge in tools/fleet_agg.py under role
    'router' (the satellite: the fleet view shows the front door next
    to its replicas)."""
    from pytorch_vit_paper_replication_tpu.telemetry.shipper import (
        TelemetryShipper)

    fa = _load_tool("fleet_agg")
    manager, router, registry = _mk_fleet(tmp_path)
    agg = fa.FleetAggregator(stale_after_s=5.0).start()
    try:
        with manager, router:
            manager.start()
            assert manager.wait_ready(20.0)
            router.start()
            _ask(router.address, ["ship.jpg"])
            shipper = TelemetryShipper(
                ("127.0.0.1", agg.port), worker_id="router-0",
                role="router", registry=registry,
                pre_ship=router.publish_telemetry)
            assert shipper.ship_now()
            shipper.close()
            # The aggregator ingests frames on its own thread —
            # poll for arrival (same idiom as test_fleet_obs).
            deadline = time.time() + 10.0
            while time.time() < deadline:
                snap = agg.fleet_snapshot()
                if "router-0" in snap["workers"]:
                    break
                time.sleep(0.05)
            w = snap["workers"]["router-0"]
            assert w["role"] == "router" and w["alive"]
            assert w["gauges"]["fleet_replicas_up"] == 2
            merged = snap["merged"]["counters"]
            assert merged["fleet_route_requests_total"] >= 1
    finally:
        agg.close()


# --------------------------------------------------- one REAL replica
def test_real_replica_behind_router_bit_identical(tmp_path):
    """One REAL serve-CLI replica supervised by the manager, fronted
    by the router: the routed TSV answer and the ::probs row match
    predict_image through the shared inference-load contract —
    cross-process bit-identity, the property the rolling swap's
    re-admission probe rests on."""
    import functools

    from pytorch_vit_paper_replication_tpu.predictions import (
        load_inference_checkpoint, predict_image)

    fb = _load_tool("fleet_bench")
    ckpt, _, _ = fb.make_checkpoint(tmp_path / "ckpt", seed=0)
    classes_file = tmp_path / "classes.txt"
    classes_file.write_text("\n".join(fb.CLASSES) + "\n")
    probe = fb.make_probe_image(tmp_path / "probe.png", 32)

    model, params, transform, _spec = load_inference_checkpoint(
        ckpt, "ViT-Ti/16", len(fb.CLASSES))
    ref_label, ref_prob, ref_probs = predict_image(
        model, params, probe, list(fb.CLASSES), transform=transform)

    from tools._common import cpu_child_env
    registry = TelemetryRegistry()
    manager = ReplicaManager(
        [ReplicaSpec(rid="r0", checkpoint=str(ckpt))],
        command_factory=functools.partial(
            build_serve_command, classes_file=str(classes_file),
            preset="ViT-Ti/16", buckets="1,4"),
        env_factory=lambda spec: replica_env(spec.devices,
                                             base=cpu_child_env()),
        health_interval_s=0.25, stale_after_s=5.0,
        expected_rungs=(1, 4), registry=registry)
    router = FleetRouter(manager, registry=registry)
    with manager, router:
        manager.start()
        assert manager.wait_ready(180.0), manager.stderr_tail("r0")
        assert manager.wait_healthy("r0", 180.0, require_rungs=(1, 4))
        router.start()
        (reply,) = _ask(router.address, [str(probe)], timeout=120.0)
        path, label, prob = reply.split("\t")
        assert path == str(probe) and label == ref_label
        assert float(prob) == pytest.approx(ref_prob, abs=1e-4)
        probs_reply = json.loads(
            manager.request("r0", f"::probs {probe}", timeout_s=120.0))
        got = np.asarray(probs_reply["probs"], np.float32)
        np.testing.assert_array_equal(got, ref_probs)
