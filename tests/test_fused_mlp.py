"""Fused Pallas MLP kernels (ops/fused_mlp.py) — parity vs the XLA path.

Runs the REAL kernel code under the Pallas interpreter (the wrappers
auto-select interpret mode off-TPU), mirroring how test_ops.py exercises
the flash-attention kernel. Reference semantics: the MLP half of the
encoder block, reference ``models/vit.py:100-131`` (+ residual at :168).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_vit_paper_replication_tpu.configs import vit_ti16
from pytorch_vit_paper_replication_tpu.models.vit import (
    MLPBlock, TransformerEncoderBlock)
from pytorch_vit_paper_replication_tpu.ops.dropout import (
    _threshold, derive_positional_seed, positional_keep_u8, quantized_rate)
from pytorch_vit_paper_replication_tpu.ops.fused_mlp import (
    fused_ln_mlp_residual, fused_mlp)

from conftest import requires_shard_map

D, F = 64, 256


def _params(key, d=D, f=F):
    ks = jax.random.split(key, 7)
    return dict(
        x=jax.random.normal(ks[0], (2, 25, d), jnp.float32),
        gamma=1.0 + 0.1 * jax.random.normal(ks[1], (d,)),
        beta=0.1 * jax.random.normal(ks[2], (d,)),
        w1=jax.random.normal(ks[3], (d, f)) * 0.1,
        b1=0.1 * jax.random.normal(ks[4], (f,)),
        w2=jax.random.normal(ks[5], (f, d)) * 0.1,
        b2=0.1 * jax.random.normal(ks[6], (d,)),
    )


def _ref_mlp(x, w1, b1, w2, b2):
    g = jax.nn.gelu(x @ w1 + b1, approximate=False)
    return g @ w2 + b2


def _ref_ln_mlp_res(x, gamma, beta, w1, b1, w2, b2, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    c = x32 - mu
    var = (c * c).mean(-1, keepdims=True)
    y = c * jax.lax.rsqrt(var + eps) * gamma + beta
    return x32 + _ref_mlp(y, w1, b1, w2, b2)


def test_fused_mlp_forward_matches_xla(rng):
    p = _params(rng)
    out = fused_mlp(p["x"], p["w1"], p["b1"], p["w2"], p["b2"])
    ref = _ref_mlp(p["x"], p["w1"], p["b1"], p["w2"], p["b2"])
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_fused_mlp_grads_match_xla(rng):
    p = _params(rng)
    ct = jax.random.normal(jax.random.fold_in(rng, 1), p["x"].shape)
    args = (p["x"], p["w1"], p["b1"], p["w2"], p["b2"])
    g_f = jax.grad(lambda a: (fused_mlp(*a) * ct).sum())(args)
    g_r = jax.grad(lambda a: (_ref_mlp(*a) * ct).sum())(args)
    for a, b in zip(g_f, g_r):
        np.testing.assert_allclose(a, b, atol=2e-3, rtol=2e-3)


def test_fused_ln_mlp_residual_forward(rng):
    p = _params(rng)
    out = fused_ln_mlp_residual(**p)
    ref = _ref_ln_mlp_res(**p)
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_fused_ln_mlp_residual_grads(rng):
    p = _params(rng)
    ct = jax.random.normal(jax.random.fold_in(rng, 1), p["x"].shape)
    keys = list(p)
    g_f = jax.grad(lambda a: (fused_ln_mlp_residual(
        **dict(zip(keys, a))) * ct).sum())(tuple(p.values()))
    g_r = jax.grad(lambda a: (_ref_ln_mlp_res(
        **dict(zip(keys, a))) * ct).sum())(tuple(p.values()))
    for a, b, name in zip(g_f, g_r, keys):
        np.testing.assert_allclose(a, b, atol=2e-3, rtol=2e-3,
                                   err_msg=f"grad {name}")


def test_fused_mlp_dropout_matches_positional_mask(rng):
    """The in-kernel hidden dropout equals a hand-applied positional-hash
    mask (same definition the flash kernel shares), forward AND backward."""
    p = _params(rng)
    drng = jax.random.fold_in(rng, 7)
    seed = derive_positional_seed(drng)
    thr = _threshold(0.3)
    inv = 256.0 / (256.0 - thr)
    x2 = p["x"].reshape(-1, D)
    keep = positional_keep_u8(seed[0], jnp.int32(0),
                              jnp.arange(x2.shape[0])[:, None],
                              jnp.arange(F)[None, :], thr)

    def ref(a):
        x, w1, b1, w2, b2 = a
        g = jax.nn.gelu(x.reshape(-1, D) @ w1 + b1, approximate=False)
        g = jnp.where(keep, g * inv, 0.0)
        return (g @ w2 + b2).reshape(x.shape)

    args = (p["x"], p["w1"], p["b1"], p["w2"], p["b2"])
    out = fused_mlp(*args, dropout_rate=0.3, dropout_rng=drng,
                    deterministic=False)
    np.testing.assert_allclose(out, ref(args), atol=1e-4, rtol=1e-4)

    ct = jax.random.normal(jax.random.fold_in(rng, 1), p["x"].shape)
    g_f = jax.grad(lambda a: (fused_mlp(
        *a, dropout_rate=0.3, dropout_rng=drng,
        deterministic=False) * ct).sum())(args)
    g_r = jax.grad(lambda a: (ref(a) * ct).sum())(args)
    for a, b in zip(g_f, g_r):
        np.testing.assert_allclose(a, b, atol=2e-3, rtol=2e-3)


def test_fused_ln_mlp_dropout_statistics(rng):
    """Both dropout sites drop at the quantized rate and the output is
    mean-preserving in expectation (spot-check via drop fraction on the
    hidden mask's direct evaluation)."""
    thr = _threshold(0.25)
    keep = positional_keep_u8(jnp.int32(1234), jnp.int32(0),
                              jnp.arange(512)[:, None],
                              jnp.arange(512)[None, :], thr)
    frac = float(jnp.mean(keep))
    assert abs(frac - (1 - quantized_rate(0.25))) < 0.01
    # hidden (bh=0) and output (bh=1) masks are distinct streams
    keep2 = positional_keep_u8(jnp.int32(1234), jnp.int32(1),
                               jnp.arange(512)[:, None],
                               jnp.arange(512)[None, :], thr)
    assert float(jnp.mean(keep == keep2)) < 0.9


def test_fused_mlp_nondivisible_rows_padded(rng):
    """Row counts not divisible by the block size pad correctly, and the
    padded rows contribute nothing to weight grads."""
    ks = jax.random.split(rng, 5)
    x = jax.random.normal(ks[0], (3, 13, D), jnp.float32)  # 39 rows
    w1 = jax.random.normal(ks[1], (D, F)) * 0.1
    b1 = jnp.zeros((F,))
    w2 = jax.random.normal(ks[2], (F, D)) * 0.1
    b2 = jnp.zeros((D,))
    out = fused_mlp(x, w1, b1, w2, b2, block_rows=16)
    ref = _ref_mlp(x, w1, b1, w2, b2)
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)
    g_f = jax.grad(lambda w: fused_mlp(x, w, b1, w2, b2,
                                       block_rows=16).sum())(w1)
    g_r = jax.grad(lambda w: _ref_mlp(x, w, b1, w2, b2).sum())(w1)
    np.testing.assert_allclose(g_f, g_r, atol=2e-3, rtol=2e-3)


# --------------------------------------------------------------------------
# Model integration: mlp_impl paths agree and share one param tree
# --------------------------------------------------------------------------

def _block_params_and_input(rng, impl):
    cfg = vit_ti16(num_classes=10, mlp_impl=impl, dtype="float32")
    block = TransformerEncoderBlock(cfg)
    x = jax.random.normal(jax.random.fold_in(rng, 2),
                          (2, 17, cfg.embedding_dim), jnp.float32)
    params = block.init(rng, x)["params"]
    return cfg, block, params, x


def test_mlp_impl_param_trees_identical(rng):
    _, _, p_xla, _ = _block_params_and_input(rng, "xla")
    _, _, p_fused, _ = _block_params_and_input(rng, "fused")
    assert (jax.tree_util.tree_structure(p_xla)
            == jax.tree_util.tree_structure(p_fused))
    for a, b in zip(jax.tree_util.tree_leaves(p_xla),
                    jax.tree_util.tree_leaves(p_fused)):
        assert a.shape == b.shape and a.dtype == b.dtype
        np.testing.assert_allclose(a, b)  # same init stream


def test_mlp_impl_forward_parity(rng):
    """fused and xla encoder blocks agree (deterministic mode) on the SAME
    params — the whole point of keeping param trees identical."""
    cfg_x, block_x, params, x = _block_params_and_input(rng, "xla")
    cfg_f = cfg_x.replace(mlp_impl="fused")
    block_f = TransformerEncoderBlock(cfg_f)
    out_x = block_x.apply({"params": params}, x)
    out_f = block_f.apply({"params": params}, x)
    np.testing.assert_allclose(out_f, out_x, atol=1e-4, rtol=1e-4)


def test_mlp_impl_grad_parity(rng):
    cfg_x, block_x, params, x = _block_params_and_input(rng, "xla")
    block_f = TransformerEncoderBlock(cfg_x.replace(mlp_impl="fused"))
    g_x = jax.grad(lambda p: block_x.apply({"params": p}, x).sum())(params)
    g_f = jax.grad(lambda p: block_f.apply({"params": p}, x).sum())(params)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(g_x),
            jax.tree_util.tree_leaves_with_path(g_f)):
        np.testing.assert_allclose(a, b, atol=3e-3, rtol=3e-3,
                                   err_msg=str(ka))


@requires_shard_map
def test_mlp_impl_manual_tp_core_mode(rng):
    """Under a tp_axis (shard_map manual TP) the fused path uses the core
    kernel with the psum outside — forward must still match xla."""
    from jax.sharding import Mesh
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    cfg = vit_ti16(num_classes=10, dtype="float32")
    x = jax.random.normal(jax.random.fold_in(rng, 2),
                          (2, 17, cfg.embedding_dim), jnp.float32)
    block = MLPBlock(cfg)
    params = block.init(rng, x)["params"]

    devs = np.array(jax.devices()[:2]).reshape(2)
    mesh = Mesh(devs, ("model",))
    local_cfg = cfg.replace(mlp_size=cfg.mlp_size // 2)

    def run(impl):
        lcfg = local_cfg.replace(mlp_impl=impl)

        def shard_fn(p_local, x):
            return MLPBlock(lcfg, tp_axis="model").apply(
                {"params": p_local}, x)

        p_sharded = {
            "norm": params["norm"],
            "fc1": {"kernel": params["fc1"]["kernel"],
                    "bias": params["fc1"]["bias"]},
            # Replicated fc2 bias fed as b/tp so the post-fc2 psum
            # reconstructs it exactly once (pipeline.py's
            # scale_replicated_biases convention).
            "fc2": {"kernel": params["fc2"]["kernel"],
                    "bias": params["fc2"]["bias"] / 2.0},
        }
        fn = shard_map(
            shard_fn, mesh=mesh,
            in_specs=({"norm": P(), "fc1": {"kernel": P(None, "model"),
                                            "bias": P("model")},
                       "fc2": {"kernel": P("model", None), "bias": P()}},
                      P()),
            out_specs=P(), check_vma=False)
        return fn(p_sharded, x)

    out_x = run("xla")
    out_f = run("fused")
    ref = block.apply({"params": params}, x)
    np.testing.assert_allclose(out_x, ref, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(out_f, ref, atol=1e-4, rtol=1e-4)


def test_fused_mlp_dropout_needs_rng(rng):
    p = _params(rng)
    with pytest.raises(ValueError, match="dropout_rng"):
        fused_mlp(p["x"], p["w1"], p["b1"], p["w2"], p["b2"],
                  dropout_rate=0.1, deterministic=False)


def test_fused_ln_mlp_residual_shape_check(rng):
    p = _params(rng)
    with pytest.raises(ValueError, match="residual"):
        fused_ln_mlp_residual(p["x"], p["gamma"], p["beta"],
                              p["w1"], p["b1"],
                              jnp.zeros((F, D + 8)), jnp.zeros((D + 8,)))


def test_fused_under_gspmd_mesh_train_step(devices, rng):
    """The fused MLP path composes with GSPMD dp x tp meshes (the
    non-pipeline parallel path): a full parallel train step runs and
    matches the xla-impl step's loss when dropout is off (same params,
    same batch; the kernels are numerically equivalent)."""
    import numpy as np

    from pytorch_vit_paper_replication_tpu import engine
    from pytorch_vit_paper_replication_tpu.configs import (MeshConfig,
                                                           TrainConfig)
    from pytorch_vit_paper_replication_tpu.configs import vit_s16
    from pytorch_vit_paper_replication_tpu.data import synthetic_batch
    from pytorch_vit_paper_replication_tpu.models import ViT
    from pytorch_vit_paper_replication_tpu.optim import make_optimizer
    from pytorch_vit_paper_replication_tpu.parallel.mesh import make_mesh
    from pytorch_vit_paper_replication_tpu.parallel.api import (
        make_parallel_train_step, shard_batch, shard_train_state)

    def run(impl):
        # Fresh keys per run: the donated train step consumes the state's
        # rng buffer, so a shared fixture key dies after the first run.
        key = jax.random.key(0)
        cfg = vit_s16(num_classes=10, dtype="float32", image_size=32,
                      patch_size=8, mlp_impl=impl, attn_dropout=0.0,
                      mlp_dropout=0.0, embedding_dropout=0.0)
        model = ViT(cfg)
        params = model.init(key, jnp.zeros((1, 32, 32, 3)))["params"]
        tx = make_optimizer(TrainConfig(), total_steps=100)
        state = engine.TrainState.create(apply_fn=model.apply,
                                         params=params, tx=tx,
                                         rng=jax.random.key(1))
        mesh = make_mesh(MeshConfig(data=4, model=2))
        state = shard_train_state(state, mesh)
        step = make_parallel_train_step(state, mesh)
        batch = shard_batch(jax.tree.map(
            jnp.asarray, synthetic_batch(16, 32, 10)), mesh)
        state2, m = step(state, batch)
        return float(m["loss_sum"]), float(jax.device_get(
            jnp.sum(jnp.abs(state2.params["head"]["kernel"]))))

    loss_f, head_f = run("fused")
    loss_x, head_x = run("xla")
    np.testing.assert_allclose(loss_f, loss_x, rtol=1e-4)
    np.testing.assert_allclose(head_f, head_x, rtol=1e-3)
