"""Ulysses (all-to-all) sequence parallelism — exactness vs full
attention, parity with ring attention (including bit-identical dropout
masks), composition with dp/tp, gradients, dispatch, and the CLI."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_vit_paper_replication_tpu import parallel
from pytorch_vit_paper_replication_tpu.configs import MeshConfig

from conftest import requires_shard_map

pytestmark = requires_shard_map


def _qkv(seed, b, t, h, d):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d)) for k in ks)


def test_ulysses_exact(devices):
    """Ulysses over the 'seq' axis equals full attention (h=8 divides)."""
    mesh = parallel.make_mesh(MeshConfig(data=1, model=1, seq=8))
    q, k, v = _qkv(0, 2, 64, 8, 16)
    ref = jax.nn.dot_product_attention(q, k, v)
    out = parallel.make_ulysses_attention(mesh)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_ulysses_with_dp_and_tp(devices):
    """Composes with DP and TP on a 2x2x2 mesh (heads sharded over model
    AND re-split over seq)."""
    mesh = parallel.make_mesh(MeshConfig(data=2, model=2, seq=2))
    q, k, v = _qkv(1, 4, 32, 4, 16)
    ref = jax.nn.dot_product_attention(q, k, v)
    out = parallel.make_ulysses_attention(mesh, head_axis="model")(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_ulysses_matches_ring(devices):
    """The two SP strategies compute the same attention (deterministic)."""
    mesh = parallel.make_mesh(MeshConfig(data=2, model=1, seq=4))
    q, k, v = _qkv(2, 2, 64, 4, 16)
    out_u = parallel.make_ulysses_attention(mesh)(q, k, v)
    out_r = parallel.make_ring_attention(mesh)(q, k, v)
    np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_r),
                               rtol=1e-4, atol=1e-5)


def test_ulysses_gradient(devices):
    """all_to_all is differentiable; backward equals full attention's."""
    mesh = parallel.make_mesh(MeshConfig(data=2, model=1, seq=4))
    q, k, v = _qkv(3, 2, 32, 4, 16)
    uly = parallel.make_ulysses_attention(mesh)

    def loss_u(args):
        return jnp.sum(jnp.sin(uly(*args)))

    def loss_f(args):
        return jnp.sum(jnp.sin(jax.nn.dot_product_attention(*args)))

    g_u = jax.grad(loss_u)((q, k, v))
    g_f = jax.grad(loss_f)((q, k, v))
    for name, a, b in zip("qkv", g_u, g_f):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-4, err_msg=f"d{name}")


def test_ulysses_dropout_mask_identical_to_ring(devices):
    """The LOAD-BEARING noise claim: for one seed, ulysses and ring drop
    the exact same attention-weight elements (both hash GLOBAL
    coordinates), so switching SP strategy never changes the training
    noise. Recovered via the v=identity trick (q=k=0 -> output rows ARE
    the dropped weight rows)."""
    rate, b, h, t = 0.25, 2, 4, 64
    rng = jax.random.key(5)
    z = jnp.zeros((b, t, h, t), jnp.float32)
    eye = jnp.broadcast_to(jnp.eye(t, dtype=jnp.float32)[None, :, None, :],
                           (b, t, h, t))
    mesh = parallel.make_mesh(MeshConfig(data=2, model=1, seq=4))
    w_u = np.asarray(parallel.make_ulysses_attention(
        mesh, dropout_rate=rate, dropout_rng=rng,
        deterministic=False)(z, z, eye))
    w_r = np.asarray(parallel.make_ring_attention(
        mesh, dropout_rate=rate, dropout_rng=rng,
        deterministic=False)(z, z, eye))
    np.testing.assert_array_equal(w_u > 0, w_r > 0)
    np.testing.assert_allclose(w_u, w_r, rtol=1e-5)
    frac = 1.0 - (w_u > 0).mean()
    assert abs(frac - 0.25) < 0.02


def test_ulysses_rejects_indivisible_heads(devices):
    """h=2 on seq=4: a clear error from the op (the DISPATCH falls back
    to XLA instead — next test)."""
    mesh = parallel.make_mesh(MeshConfig(data=2, model=1, seq=4))
    q, k, v = _qkv(4, 2, 32, 2, 16)
    with pytest.raises(ValueError, match="divisible"):
        parallel.make_ulysses_attention(mesh)(q, k, v)


def test_dispatch_ulysses_and_head_fallback(devices):
    """sequence_parallel(sp_impl='ulysses') routes through the all-to-all
    path when heads divide, and warns+falls back to the gathered XLA path
    when they don't — never a crash mid-model."""
    import warnings

    from pytorch_vit_paper_replication_tpu.ops.attention import (
        dot_product_attention, sequence_parallel)

    mesh = parallel.make_mesh(MeshConfig(data=2, model=1, seq=4))
    q, k, v = _qkv(5, 2, 32, 4, 16)
    ref = jax.nn.dot_product_attention(q, k, v)
    with sequence_parallel(mesh, sp_impl="ulysses"):
        out = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)

    qs, ks_, vs = _qkv(6, 2, 32, 2, 16)  # h=2 not divisible by 4
    with sequence_parallel(mesh, sp_impl="ulysses"):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out2 = dot_product_attention(qs, ks_, vs)
    assert any("ulysses" in str(x.message) for x in w)
    np.testing.assert_allclose(
        np.asarray(out2), np.asarray(jax.nn.dot_product_attention(
            qs, ks_, vs)), rtol=2e-2, atol=2e-2)


def test_cli_trains_with_ulysses(devices, tmp_path):
    """--sp-impl ulysses end-to-end through the CLI. ViT-S/16 (6 heads,
    divisible by seq=2) with gap pooling for an even token count."""
    from pytorch_vit_paper_replication_tpu.train import main as train_main

    results = train_main([
        "--synthetic", "--preset", "ViT-S/16", "--image-size", "32",
        "--patch-size", "16", "--pool", "gap", "--dtype", "float32",
        "--attention", "xla", "--epochs", "1", "--batch-size", "8",
        "--mesh-data", "4", "--mesh-seq", "2", "--sp-impl", "ulysses",
        "--metrics-jsonl", str(tmp_path / "m.jsonl"),
    ])
    assert len(results["train_loss"]) == 1
    assert math.isfinite(results["train_loss"][0])
