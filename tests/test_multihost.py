"""A REAL 2-process CPU cluster (VERDICT r2 #6): ``jax.distributed``
coordinator + 4 virtual devices per process = the same 8-device 'data'
mesh the rest of the suite uses, but spanning two OS processes — so
``initialize_multi_host``, the per-host loader shards, and
``shard_batch``'s ``make_array_from_process_local_data`` branch all
execute for real instead of being single-process dead code."""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

WORKER = Path(__file__).with_name("multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def cluster_dataset(tmp_path_factory):
    from pytorch_vit_paper_replication_tpu.data import (
        make_synthetic_image_folder)

    root = tmp_path_factory.mktemp("mh_dataset")
    # 48 train images -> 24/host -> 3 local batches of 8 (global 16);
    # 9 test images -> ceil(9/2)=5/host with one pad row -> ragged final
    # batch, exercising the pad+mask exact-eval path across hosts.
    return make_synthetic_image_folder(root, train_per_class=16,
                                       test_per_class=3, image_size=32)


def test_two_process_cluster_matches_single_process(cluster_dataset,
                                                    tmp_path):
    train_dir, test_dir = cluster_dataset
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own 4-device split
    repo_root = str(WORKER.parent.parent)
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    outs = [tmp_path / f"worker{i}.json" for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER),
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(i),
             "--train-dir", str(train_dir), "--test-dir", str(test_dir),
             "--out", str(outs[i])],
            env=env, cwd=str(WORKER.parent.parent),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    logs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("2-process cluster timed out (coordinator hang?)")
        logs.append(out)
    for i, p in enumerate(procs):
        assert p.returncode == 0, \
            f"worker {i} failed:\n{logs[i][-4000:]}"

    results = [json.loads(o.read_text()) for o in outs]
    for i, r in enumerate(results):
        assert r["process_index"] == i
        assert r["process_count"] == 2
        assert r["num_devices"] == 8
        assert r["final_step"] == r["steps_per_epoch"] * 2

    # Both processes computed the same GLOBAL quantities (metrics are
    # replicated outputs of the same SPMD program) — bit-exact agreement.
    np.testing.assert_array_equal(results[0]["train_losses"],
                                  results[1]["train_losses"])
    assert results[0]["eval_loss"] == results[1]["eval_loss"]
    assert results[0]["param_norm"] == results[1]["param_norm"]

    # And the cluster's training equals the single-process 8-device run of
    # the identical recipe (same global shuffle, same global batches; row
    # order within a batch differs by host interleaving, so agreement is
    # up to fp32 reduction order).
    from multihost_worker import run

    ref = run(train_dir, test_dir)
    assert ref["process_count"] == 1
    assert ref["steps_per_epoch"] == results[0]["steps_per_epoch"]
    np.testing.assert_allclose(results[0]["train_losses"],
                               ref["train_losses"], rtol=2e-5)
    np.testing.assert_allclose(results[0]["eval_loss"], ref["eval_loss"],
                               rtol=2e-5)
    assert results[0]["eval_count"] == ref["eval_count"] == 9.0
    assert results[0]["eval_acc"] == ref["eval_acc"]
    np.testing.assert_allclose(results[0]["param_norm"], ref["param_norm"],
                               rtol=2e-5)
