"""A REAL 2-process CPU cluster (VERDICT r2 #6): ``jax.distributed``
coordinator + 4 virtual devices per process = the same 8-device 'data'
mesh the rest of the suite uses, but spanning two OS processes — so
``initialize_multi_host``, the per-host loader shards, and
``shard_batch``'s ``make_array_from_process_local_data`` branch all
execute for real instead of being single-process dead code."""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from conftest import requires_multiprocess_cpu

# jax 0.4.x: "Multiprocess computations aren't implemented on the CPU
# backend" — a known environment gap, reported as SKIPPED, not FAILED.
pytestmark = requires_multiprocess_cpu

WORKER = Path(__file__).with_name("multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def cluster_dataset(tmp_path_factory):
    from pytorch_vit_paper_replication_tpu.data import (
        make_synthetic_image_folder)

    root = tmp_path_factory.mktemp("mh_dataset")
    # 48 train images -> 24/host -> 3 local batches of 8 (global 16);
    # 9 test images -> ceil(9/2)=5/host with one pad row -> ragged final
    # batch, exercising the pad+mask exact-eval path across hosts.
    return make_synthetic_image_folder(root, train_per_class=16,
                                       test_per_class=3, image_size=32)


def _run_cluster(train_dir, test_dir, tmp_path, tag: str,
                 extra_args: list = ()) -> list:
    """Spawn a 2-process jax.distributed cluster of the worker script and
    return both workers' result dicts."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own 4-device split
    repo_root = str(WORKER.parent.parent)
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    outs = [tmp_path / f"worker_{tag}{i}.json" for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER),
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(i),
             "--train-dir", str(train_dir), "--test-dir", str(test_dir),
             "--out", str(outs[i]), *extra_args],
            env=env, cwd=str(WORKER.parent.parent),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    logs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("2-process cluster timed out (coordinator hang?)")
        logs.append(out)
    for i, p in enumerate(procs):
        assert p.returncode == 0, \
            f"worker {i} ({tag}) failed:\n{logs[i][-4000:]}"
    return [json.loads(o.read_text()) for o in outs]


def test_two_process_cluster_matches_single_process(cluster_dataset,
                                                    tmp_path):
    train_dir, test_dir = cluster_dataset
    results = _run_cluster(train_dir, test_dir, tmp_path, "base")
    for i, r in enumerate(results):
        assert r["process_index"] == i
        assert r["process_count"] == 2
        assert r["num_devices"] == 8
        assert r["final_step"] == r["steps_per_epoch"] * 2

    # Both processes computed the same GLOBAL quantities (metrics are
    # replicated outputs of the same SPMD program) — bit-exact agreement.
    np.testing.assert_array_equal(results[0]["train_losses"],
                                  results[1]["train_losses"])
    assert results[0]["eval_loss"] == results[1]["eval_loss"]
    assert results[0]["param_norm"] == results[1]["param_norm"]

    # And the cluster's training equals the single-process 8-device run of
    # the identical recipe (same global shuffle, same global batches; row
    # order within a batch differs by host interleaving, so agreement is
    # up to fp32 reduction order).
    from multihost_worker import run

    ref = run(train_dir, test_dir)
    assert ref["process_count"] == 1
    assert ref["steps_per_epoch"] == results[0]["steps_per_epoch"]
    np.testing.assert_allclose(results[0]["train_losses"],
                               ref["train_losses"], rtol=2e-5)
    np.testing.assert_allclose(results[0]["eval_loss"], ref["eval_loss"],
                               rtol=2e-5)
    assert results[0]["eval_count"] == ref["eval_count"] == 9.0
    assert results[0]["eval_acc"] == ref["eval_acc"]
    np.testing.assert_allclose(results[0]["param_norm"], ref["param_norm"],
                               rtol=2e-5)


@pytest.mark.parametrize("tag,mesh_args", [
    ("dp", []),                       # replicated state over the dp mesh
    ("tp", ["--mesh-model", "2"]),    # MODEL-SHARDED params/opt leaves:
                                      # orbax save/restore of genuinely
                                      # partitioned multi-process state
])
def test_two_process_checkpoint_resume_matches_uninterrupted(
        cluster_dataset, tmp_path, tag, mesh_args):
    """VERDICT r3 #4: the managed Orbax Checkpointer's multi-PROCESS path —
    collective save on a shared directory mid-run (mid-epoch, so the
    loader's skip math is exercised too), both processes torn down, a
    fresh 2-process cluster restores and finishes; final state must match
    the uninterrupted 2-process run bit-for-bit (same recipe, same global
    shuffle, deterministic CPU math). Parametrized over the mesh so the
    dp (replicated leaves) and dp x tp (model-sharded leaves) Orbax
    paths get identical assertions."""
    train_dir, test_dir = cluster_dataset
    ckpt_dir = tmp_path / f"shared_ckpt_{tag}"  # both workers write here

    full = _run_cluster(train_dir, test_dir, tmp_path, f"{tag}full",
                        mesh_args)

    stop_at = 4  # 3 steps/epoch -> mid-epoch-2 (1 full epoch + 1 step)
    part = _run_cluster(train_dir, test_dir, tmp_path, f"{tag}part",
                        mesh_args + ["--checkpoint-dir", str(ckpt_dir),
                                     "--stop-after", str(stop_at)])
    for r in part:
        assert r["stopped_early"] and r["final_step"] == stop_at
    # The preempted prefix already matches the uninterrupted run.
    np.testing.assert_array_equal(part[0]["train_losses"],
                                  full[0]["train_losses"][:stop_at])

    resumed = _run_cluster(train_dir, test_dir, tmp_path, f"{tag}res",
                           mesh_args + ["--checkpoint-dir", str(ckpt_dir),
                                        "--resume"])
    for r in resumed:
        assert not r["stopped_early"]
        assert r["final_step"] == full[0]["final_step"]
    # Continuation losses equal the uninterrupted run's tail, and the
    # final model/eval are identical — restore round-tripped params,
    # opt_state (LR-schedule position), step, and rng exactly.
    np.testing.assert_array_equal(resumed[0]["train_losses"],
                                  full[0]["train_losses"][stop_at:])
    assert resumed[0]["param_norm"] == full[0]["param_norm"]
    assert resumed[0]["eval_loss"] == full[0]["eval_loss"]
    assert resumed[0]["eval_acc"] == full[0]["eval_acc"]
    # Both processes of the resumed cluster agree (replicated outputs).
    assert resumed[0]["param_norm"] == resumed[1]["param_norm"]
