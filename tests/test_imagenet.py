"""ImageNet-scale pipeline: pack -> memmap shards -> array-space
augmentation -> loaders (data/imagenet.py)."""

import json

import numpy as np
import pytest

from pytorch_vit_paper_replication_tpu.data import (
    DataLoader,
    PackedShardDataset,
    create_packed_dataloaders,
    pack_image_folder,
)
from pytorch_vit_paper_replication_tpu.data.imagenet import (
    ComposeArray,
    RandomHorizontalFlipArray,
    RandomResizedCropArray,
    ThreadLocalRng,
    ToFloatArray,
    eval_center_transform,
    train_augment_transform,
)


@pytest.fixture(scope="module")
def packed_root(synthetic_folder, tmp_path_factory):
    train_dir, test_dir = synthetic_folder
    root = tmp_path_factory.mktemp("packed")
    # Small shards to exercise the multi-shard path (18 images / 8 -> 3).
    pack_image_folder(train_dir, root / "train", pack_size=48,
                      images_per_shard=8)
    pack_image_folder(test_dir, root / "test", pack_size=48,
                      images_per_shard=8)
    return root


def test_pack_and_read_roundtrip(packed_root):
    ds = PackedShardDataset(packed_root / "train")
    assert ds.classes == ["pizza", "steak", "sushi"]
    assert len(ds) == 18
    arr, label = ds[0]
    assert arr.shape == (48, 48, 3) and arr.dtype == np.uint8
    assert label in (0, 1, 2)
    # Multi-shard layout: record 17 lives in the third shard.
    arr17, _ = ds[17]
    assert arr17.shape == (48, 48, 3)
    with pytest.raises(IndexError):
        ds[18]


def test_packed_readahead_hint_bounded(packed_root, monkeypatch):
    """r5: the madvise(WILLNEED) readahead hint fires only when the pack
    fits in half of MemAvailable, and reads are identical either way."""
    import pytorch_vit_paper_replication_tpu.data.imagenet as im

    # Force the fits-in-RAM branch so the positive case is really
    # asserted (this is a Linux CI box: madvise must work).
    monkeypatch.setattr(im, "_mem_available_bytes", lambda: 1 << 40)
    ds = PackedShardDataset(packed_root / "train")
    assert ds.readahead is True
    monkeypatch.setattr(im, "_mem_available_bytes", lambda: 0)
    ds2 = PackedShardDataset(packed_root / "train")
    assert ds2.readahead is False
    a, la = ds[5]
    b, lb = ds2[5]
    np.testing.assert_array_equal(a, b)
    assert la == lb


def test_pack_index_consistency_checked(packed_root, tmp_path):
    import shutil

    bad = tmp_path / "bad"
    shutil.copytree(packed_root / "train", bad)
    meta = json.loads((bad / "index.json").read_text())
    meta["labels"] = meta["labels"][:-1]
    (bad / "index.json").write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="inconsistent"):
        PackedShardDataset(bad)


def test_packed_labels_match_image_folder(synthetic_folder, packed_root):
    """Packing preserves the (sorted-subdir) class/label assignment."""
    from pytorch_vit_paper_replication_tpu.data import ImageFolderDataset

    train_dir, _ = synthetic_folder
    ref = ImageFolderDataset(train_dir)
    ds = PackedShardDataset(packed_root / "train")
    assert [ds[i][1] for i in range(len(ds))] == \
        [ref.samples[i][1] for i in range(len(ref))]


def test_random_resized_crop_array():
    rng = np.random.default_rng(0)
    crop = RandomResizedCropArray(32, rng=rng)
    arr = np.arange(64 * 48 * 3, dtype=np.uint8).reshape(64, 48, 3)
    outs = [crop(arr) for _ in range(8)]
    for o in outs:
        assert o.shape == (32, 32, 3) and o.dtype == np.uint8
    # stochastic: draws differ across calls
    assert any(not np.array_equal(outs[0], o) for o in outs[1:])


def test_random_resized_crop_fallback_box_within_bounds():
    """Extreme ratio bounds force the 10-try fallback; box must stay legal."""
    crop = RandomResizedCropArray(16, scale=(0.99, 1.0), ratio=(10.0, 11.0),
                                  rng=np.random.default_rng(1))
    top, left, ch, cw = crop._sample_box(40, 40)
    assert 0 <= top <= 40 - ch and 0 <= left <= 40 - cw
    out = crop(np.zeros((40, 40, 3), np.uint8))
    assert out.shape == (16, 16, 3)


def test_flip_array_flips():
    arr = np.arange(2 * 4 * 3, dtype=np.uint8).reshape(2, 4, 3)
    always = RandomHorizontalFlipArray(p=1.0)
    np.testing.assert_array_equal(always(arr), arr[:, ::-1])
    never = RandomHorizontalFlipArray(p=0.0)
    np.testing.assert_array_equal(never(arr), arr)


def test_to_float_array_normalize():
    arr = np.full((2, 2, 3), 128, np.uint8)
    plain = ToFloatArray(normalize=False)(arr)
    np.testing.assert_allclose(plain, 128 / 255.0, rtol=1e-6)
    norm = ToFloatArray(normalize=True)(arr)
    assert norm.dtype == np.float32
    assert abs(norm.mean()) < 1.0  # roughly centered


def test_compose_array_stochastic_flag():
    det = ComposeArray([ToFloatArray()])
    assert not det.stochastic
    aug = train_augment_transform(32)
    assert aug.stochastic
    assert not eval_center_transform(32).stochastic


def test_thread_local_rng_distinct_streams():
    import concurrent.futures as cf

    rng = ThreadLocalRng(123)
    with cf.ThreadPoolExecutor(4) as pool:
        draws = list(pool.map(lambda _: rng.random(), range(64)))
    assert len(set(draws)) == len(draws)  # no duplicated draws across threads


def test_create_packed_dataloaders_end_to_end(packed_root):
    train_dl, test_dl, classes = create_packed_dataloaders(
        packed_root / "train", packed_root / "test",
        image_size=32, batch_size=6, seed=0)
    assert classes == ["pizza", "steak", "sushi"]
    batches = list(train_dl)
    assert all(b["image"].shape == (6, 32, 32, 3) for b in batches)
    assert all(b["image"].dtype == np.float32 for b in batches)
    # augmentation is live: epoch 2 sees different arrays than epoch 1
    first_epoch = batches[0]["image"]
    batches2 = list(train_dl)
    assert not np.array_equal(first_epoch, batches2[0]["image"])
    # eval: deterministic + padded/complete
    eval_batches = list(test_dl)
    n = sum(b["label"].shape[0] for b in eval_batches)
    assert n == len(PackedShardDataset(packed_root / "test"))


def test_packed_cli_smoke(packed_root, tmp_path):
    """train.py --dataset packed end-to-end on a tiny config."""
    from pytorch_vit_paper_replication_tpu.train import main

    results = main([
        "--dataset", "packed",
        "--train-dir", str(packed_root / "train"),
        "--test-dir", str(packed_root / "test"),
        "--preset", "ViT-Ti/16", "--image-size", "32",
        "--patch-size", "16", "--dtype", "float32",
        "--epochs", "1", "--batch-size", "8", "--mesh-data", "8",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ])
    assert len(results["train_loss"]) == 1
    assert np.isfinite(results["train_loss"][0])


def test_packed_cli_refuses_image_size_above_pack_size(packed_root):
    """ADVICE r2: --image-size > pack_size would train on crop-then-upscale
    pixels while predict resizes the original — refuse instead of silently
    diverging."""
    import pytest

    from pytorch_vit_paper_replication_tpu.train import main

    with pytest.raises(SystemExit, match="pack"):
        main([
            "--dataset", "packed",
            "--train-dir", str(packed_root / "train"),
            "--test-dir", str(packed_root / "test"),
            "--preset", "ViT-Ti/16", "--image-size", "64",
            "--patch-size", "16", "--dtype", "float32",
            "--epochs", "1", "--batch-size", "8", "--mesh-data", "8",
        ])


def test_pack_cli(synthetic_folder, tmp_path, capsys):
    from pytorch_vit_paper_replication_tpu.data.pack import main

    train_dir, _ = synthetic_folder
    out = main([str(train_dir), str(tmp_path / "out"), "--pack-size", "32",
                "--shard-images", "5"])
    assert (out / "index.json").is_file()
    assert "packed 18 images" in capsys.readouterr().out


def test_predict_transform_matches_packed_eval(tmp_path):
    """The transform.json spec recorded by the packed branch (pretrained
    pipeline with resize_size=pack_size) must preprocess a non-square image
    to exactly what pack + eval_center_transform produced in training."""
    from PIL import Image

    from pytorch_vit_paper_replication_tpu.data.imagenet import _PackTransform
    from pytorch_vit_paper_replication_tpu.data.transforms import (
        make_transform)

    rng = np.random.default_rng(0)
    img = Image.fromarray(rng.integers(0, 255, (60, 90, 3), np.uint8), "RGB")

    packed_eval = eval_center_transform(32, normalize=False)(
        _PackTransform(48)(img))
    predict_side = make_transform(image_size=32, pretrained=True,
                                  normalize=False, resize_size=48)(img)
    np.testing.assert_allclose(predict_side, packed_eval, atol=1e-6)


def test_packed_cli_records_transform_spec(packed_root, tmp_path):
    from pytorch_vit_paper_replication_tpu.train import main

    main([
        "--dataset", "packed",
        "--train-dir", str(packed_root / "train"),
        "--test-dir", str(packed_root / "test"),
        "--preset", "ViT-Ti/16", "--image-size", "32",
        "--patch-size", "16", "--dtype", "float32",
        "--epochs", "1", "--batch-size", "8", "--mesh-data", "8",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ])
    spec = json.loads((tmp_path / "ckpt" / "transform.json").read_text())
    assert spec["pretrained"] is True
    assert spec["resize_size"] == 48  # the fixture's pack_size


def test_packed_loader_multi_host_shards_are_disjoint(packed_root):
    """Per-host shards of a packed dataset partition the epoch (the
    multi-host contract the image-folder loader already guarantees)."""
    ds = PackedShardDataset(packed_root / "train")
    seen = []
    for pi in range(2):
        dl = DataLoader(ds, 3, shuffle=True, seed=5,
                        process_index=pi, process_count=2)
        idxs, _ = dl._local_indices(0)
        seen.append(set(int(i) for i in idxs))
    assert not (seen[0] & seen[1])
    assert len(seen[0]) == len(seen[1])  # equal step counts per host


def test_create_packed_dataloaders_process_workers(packed_root):
    """Packed shards + augment under forked workers: memmaps are inherited
    read-only, ThreadLocalRng reseeds per child (fork-safe draws), and the
    deterministic eval path is bit-identical to thread workers."""
    train_dl, test_dl, classes = create_packed_dataloaders(
        packed_root / "train", packed_root / "test",
        image_size=32, batch_size=6, seed=0, num_workers=2,
        worker_type="process")
    assert train_dl.worker_type == "process"
    batches = list(train_dl)
    assert batches
    assert all(b["image"].shape == (6, 32, 32, 3) for b in batches)
    assert all(b["image"].dtype == np.float32 for b in batches)
    # augmentation is live across epochs in the workers too
    batches2 = list(train_dl)
    assert not np.array_equal(batches[0]["image"], batches2[0]["image"])
    # eval transform is deterministic -> forked == threaded, bitwise
    thread_dl = create_packed_dataloaders(
        packed_root / "train", packed_root / "test",
        image_size=32, batch_size=6, seed=0)[1]
    for a, b in zip(test_dl, thread_dl):
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["label"], b["label"])


def test_thread_local_rng_same_seed_reproducible_across_runs():
    """Same-seed facades replay the same draw sequence across separate
    interpreter runs (code-review r5 regression guard: mixing the pid
    into the non-forked path's seed would break --seed reproducibility
    of augmentations run-to-run). Uses a fresh subprocess so the pids
    genuinely differ."""
    import ast
    import subprocess
    import sys

    code = (
        "from pytorch_vit_paper_replication_tpu.data.transforms import "
        "ThreadLocalRng\n"
        "r = ThreadLocalRng(11)\n"
        "print(repr([float(r.uniform()) for _ in range(3)]))\n")
    out = subprocess.check_output([sys.executable, "-c", code], text=True)
    r = ThreadLocalRng(11)
    local = [float(r.uniform()) for _ in range(3)]
    assert ast.literal_eval(out.strip()) == local
