"""Engine tests: the jitted train step learns, metrics aggregate
example-weighted, and the full train() loop reproduces the reference
contract (results-dict shape, per-epoch eval)."""

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_vit_paper_replication_tpu import engine
from pytorch_vit_paper_replication_tpu.configs import TrainConfig
from pytorch_vit_paper_replication_tpu.data import synthetic_batch
from pytorch_vit_paper_replication_tpu.models import ViT
from pytorch_vit_paper_replication_tpu.optim import make_optimizer


def _make_state(cfg, train_cfg, total_steps, seed=0):
    model = ViT(cfg)
    rng = jax.random.key(seed)
    x = jnp.zeros((1, cfg.image_size, cfg.image_size, 3))
    params = model.init(rng, x)["params"]
    tx = make_optimizer(train_cfg, total_steps)
    return engine.TrainState.create(
        apply_fn=model.apply, params=params, tx=tx, rng=rng)


def test_train_step_overfits_tiny_batch(tiny_config):
    """SURVEY.md §4c golden-value test: loss decreases on a tiny synthetic
    batch — the minimum end-to-end slice of §7."""
    train_cfg = TrainConfig(learning_rate=1e-3, warmup_fraction=0.1)
    state = _make_state(tiny_config, train_cfg, total_steps=30)
    step = jax.jit(engine.make_train_step(), donate_argnums=0)
    batch = synthetic_batch(16, tiny_config.image_size,
                            tiny_config.num_classes)
    batch = jax.tree.map(jnp.asarray, batch)
    first_loss = None
    for i in range(30):
        state, metrics = step(state, batch)
        loss = float(metrics["loss_sum"] / metrics["count"])
        if first_loss is None:
            first_loss = loss
    assert loss < first_loss * 0.7, (first_loss, loss)
    assert int(jax.device_get(state.step)) == 30


def test_grad_norm_reported_and_clipped(tiny_config):
    train_cfg = TrainConfig(grad_clip_norm=1.0, warmup_fraction=0.0)
    state = _make_state(tiny_config, train_cfg, total_steps=5)
    step = jax.jit(engine.make_train_step())
    batch = jax.tree.map(jnp.asarray, synthetic_batch(
        8, tiny_config.image_size, tiny_config.num_classes))
    _, metrics = step(state, batch)
    assert "grad_norm" in metrics
    assert float(metrics["grad_norm"]) > 0.0


def test_eval_step_deterministic(tiny_config):
    train_cfg = TrainConfig()
    state = _make_state(tiny_config, train_cfg, total_steps=5)
    eval_step = jax.jit(engine.make_eval_step())
    batch = jax.tree.map(jnp.asarray, synthetic_batch(
        8, tiny_config.image_size, tiny_config.num_classes))
    m1 = eval_step(state, batch)
    m2 = eval_step(state, batch)
    np.testing.assert_array_equal(np.asarray(m1["loss_sum"]),
                                  np.asarray(m2["loss_sum"]))


def test_metrics_example_weighted():
    """Accuracy must be correct/total over all examples, not the reference's
    mean-of-batch-means (engine.py:77-78) — ragged last batch weighted
    correctly (SURVEY.md §5 'metrics')."""
    logits_a = jnp.asarray([[5.0, 0.0]] * 4)   # 4 correct predictions of 0
    logits_b = jnp.asarray([[0.0, 5.0]])       # 1 wrong prediction (label 0)
    la = jnp.zeros(4, jnp.int32)
    lb = jnp.zeros(1, jnp.int32)
    m1 = engine._metrics(jnp.asarray(0.0), logits_a, la)
    m2 = engine._metrics(jnp.asarray(0.0), logits_b, lb)
    total = jax.tree.map(lambda a, b: a + b, m1, m2)
    final = engine._finalize(total)
    # Example-weighted: 4/5 = 0.8 (batch-mean-of-means would say 0.5).
    assert abs(final["acc"] - 0.8) < 1e-6


def test_train_loop_contract(tiny_config):
    """engine.train returns the reference's results-dict shape
    (reference engine.py:173) with one entry per epoch."""
    train_cfg = TrainConfig(epochs=2)
    batches = [jax.tree.map(jnp.asarray, synthetic_batch(
        8, tiny_config.image_size, tiny_config.num_classes, seed=s))
        for s in range(3)]
    state = _make_state(tiny_config, train_cfg, total_steps=6)
    state, results = engine.train(
        state, lambda: iter(batches), lambda: iter(batches[:1]),
        epochs=2, verbose=False)
    assert sorted(results) == ["test_acc", "test_loss", "train_acc",
                               "train_loss"]
    assert all(len(v) == 2 for v in results.values())
    assert int(jax.device_get(state.step)) == 6


def test_label_smoothing_loss():
    logits = jnp.asarray([[10.0, -10.0]])
    labels = jnp.asarray([0])
    hard = engine.cross_entropy_loss(logits, labels, 0.0)
    smooth = engine.cross_entropy_loss(logits, labels, 0.1)
    assert float(smooth) > float(hard)


# --- NaN guard (failure detection, SURVEY §5) ------------------------------

def _nan_guard_state(tiny_config, rng, lr=1e-3):
    from pytorch_vit_paper_replication_tpu.configs import TrainConfig
    from pytorch_vit_paper_replication_tpu.models import ViT
    from pytorch_vit_paper_replication_tpu.optim import make_optimizer

    model = ViT(tiny_config)
    params = model.init(rng, jnp.zeros(
        (1, tiny_config.image_size, tiny_config.image_size, 3)))["params"]
    tx = make_optimizer(TrainConfig(learning_rate=lr, warmup_fraction=0.0),
                        total_steps=100)
    return engine.TrainState.create(apply_fn=model.apply, params=params,
                                    tx=tx, rng=rng)


def test_nan_guard_skips_nonfinite_update(tiny_config, rng):
    state = _nan_guard_state(tiny_config, rng)
    step = jax.jit(engine.make_train_step(nan_guard=True))
    good = {"image": jnp.ones((4, tiny_config.image_size,
                               tiny_config.image_size, 3)) * 0.5,
            "label": jnp.zeros((4,), jnp.int32)}
    bad = {"image": good["image"].at[0, 0, 0, 0].set(jnp.nan),
           "label": good["label"]}

    before = jax.device_get(state.params)
    state2, m = step(state, bad)
    assert float(m["skipped"]) == 1.0
    assert float(m["count"]) == 0.0  # excluded from epoch sums
    assert float(m["loss_sum"]) == 0.0  # zeroed, not NaN*0 (= NaN)
    after = jax.device_get(state2.params)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(a, b)  # no update applied
    assert int(state2.step) == int(state.step) + 1  # step still advances

    # A following good batch updates normally.
    state3, m2 = step(state2, good)
    assert float(m2["skipped"]) == 0.0
    assert float(m2["count"]) == 4.0
    changed = any(
        not np.array_equal(a, b)
        for a, b in zip(jax.tree.leaves(after),
                        jax.tree.leaves(jax.device_get(state3.params))))
    assert changed


def test_nan_guard_off_matches_default(tiny_config, rng):
    """nan_guard=False is the plain step: identical results on good data."""
    good = {"image": jnp.ones((4, tiny_config.image_size,
                               tiny_config.image_size, 3)) * 0.5,
            "label": jnp.zeros((4,), jnp.int32)}
    s1 = _nan_guard_state(tiny_config, rng)
    s2 = _nan_guard_state(tiny_config, rng)
    a, ma = jax.jit(engine.make_train_step(nan_guard=True))(s1, good)
    b, mb = jax.jit(engine.make_train_step())(s2, good)
    np.testing.assert_allclose(float(ma["loss_sum"]), float(mb["loss_sum"]),
                               rtol=1e-6)
    for x, y in zip(jax.tree.leaves(jax.device_get(a.params)),
                    jax.tree.leaves(jax.device_get(b.params))):
        np.testing.assert_array_equal(x, y)


def test_logger_receives_epoch_mean_grad_norm(tiny_config, rng):
    logged = []

    class FakeLogger:
        def log(self, **kw):
            logged.append(kw)

    state = _nan_guard_state(tiny_config, rng)
    batch = {"image": jnp.ones((4, tiny_config.image_size,
                                tiny_config.image_size, 3)) * 0.5,
             "label": jnp.zeros((4,), jnp.int32)}
    engine.train(state, lambda: iter([batch, batch]), lambda: iter(()),
                 epochs=1, verbose=False, logger=FakeLogger())
    assert len(logged) == 1
    gn = logged[0]["grad_norm"]
    assert np.isfinite(gn) and gn > 0


def test_checkpoint_every_epochs_cadence(tiny_config, tmp_path):
    """checkpoint_every_epochs=2 saves epochs 2 and 4 only (plus the
    final-epoch guarantee) — per-epoch saves of a large state can
    dominate wall time on slow storage, so the cadence is configurable
    (the historical default 1 is unchanged)."""
    from pytorch_vit_paper_replication_tpu.checkpoint import Checkpointer

    train_cfg = TrainConfig(epochs=5)
    batches = [jax.tree.map(jnp.asarray, synthetic_batch(
        8, tiny_config.image_size, tiny_config.num_classes, seed=s))
        for s in range(2)]
    state = _make_state(tiny_config, train_cfg, total_steps=10)
    ckpt = Checkpointer(tmp_path / "ck", max_to_keep=10)
    state, _ = engine.train(
        state, lambda: iter(batches), lambda: iter(batches[:1]),
        epochs=5, verbose=False, checkpointer=ckpt,
        checkpoint_every_epochs=2)
    ckpt.wait()
    # 2 steps/epoch: epochs 2/4 (cadence) + epoch 5 (final guarantee).
    assert sorted(ckpt.all_steps()) == [4, 8, 10]
    ckpt.close()
