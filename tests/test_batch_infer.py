"""Offline batch inference (serve/offline.py + tools/batch_infer.py):
sharded all-device dispatch correctness, bit-identity vs the
single-image path (pad tails never leak), atomic progress manifests,
and SIGKILL-then-resume byte-identity of the output sink.
"""

import importlib.util
import json
import shutil
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_vit_paper_replication_tpu.data.image_folder import ArrayDataset
from pytorch_vit_paper_replication_tpu.data.imagenet import (
    PackedShardDataset, eval_center_transform)
from pytorch_vit_paper_replication_tpu.models import ViT, ViTFeatureExtractor
from pytorch_vit_paper_replication_tpu.predictions import predict_image
from pytorch_vit_paper_replication_tpu.serve.offline import (
    PROGRESS_MANIFEST, NpySink, OfflineEngine, load_progress, shard_ladder,
    sink_sha256, validate_progress, write_progress)

REPO = Path(__file__).resolve().parent.parent


def _load_tool(name: str):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "tools" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def tiny_model(tiny_config):
    cfg = tiny_config
    model = ViT(cfg)
    params = model.init(jax.random.key(0), jnp.zeros(
        (1, cfg.image_size, cfg.image_size, 3)))["params"]
    return model, params


@pytest.fixture(scope="module")
def tiny_pack(tmp_path_factory):
    """A 13-record 32px synthetic pack — 13 exercises the padded,
    masked tail chunk on every ladder this file uses."""
    sc = _load_tool("scale_epoch")
    root = tmp_path_factory.mktemp("bi_pack")
    return sc.make_synthetic_pack(root / "pack", records=13, pack_size=32,
                                  num_classes=3, records_per_shard=8,
                                  seed=0)


# ----------------------------------------------------------- unit pieces
def test_shard_ladder_rounds_to_device_multiples():
    assert shard_ladder((1, 8, 32, 128, 256), 8) == (8, 32, 128, 256)
    assert shard_ladder((1, 4, 8), 8) == (8,)          # dupes collapse
    assert shard_ladder((1, 8, 32), 1) == (1, 8, 32)   # identity on 1
    assert shard_ladder((3,), 4) == (4,)
    with pytest.raises(ValueError):
        shard_ladder((), 8)


def test_progress_manifest_atomic_write_and_contracts(tmp_path):
    base = {"fingerprint": "fp", "head": "probs", "total_records": 13,
            "out_dim": 3, "batch_size": 8, "ladder": [8],
            "sink": "outputs.npy", "records_done": 8, "rows_written": 8,
            "preds_bytes": None}
    write_progress(tmp_path, base)
    # atomic discipline: no temp residue next to the manifest
    assert [p.name for p in tmp_path.iterdir()] == [PROGRESS_MANIFEST]
    manifest = load_progress(tmp_path)
    assert validate_progress(
        manifest, fingerprint="fp", head="probs", total_records=13,
        out_dim=3, batch_size=8, ladder=[8]) == 8
    # every identity axis refuses a mismatched resume
    for kw in ({"fingerprint": "other"}, {"head": "features"},
               {"total_records": 14}, {"out_dim": 4},
               {"batch_size": 4}, {"ladder": [4, 8]}):
        want = dict(fingerprint="fp", head="probs", total_records=13,
                    out_dim=3, batch_size=8, ladder=[8])
        want.update(kw)
        with pytest.raises(ValueError, match="mismatch"):
            validate_progress(manifest, **want)
    # corrupt file: delete-it guidance, not a raw traceback
    (tmp_path / PROGRESS_MANIFEST).write_text("{not json")
    with pytest.raises(ValueError, match="delete"):
        load_progress(tmp_path)
    assert load_progress(tmp_path / "nowhere") is None


def test_preds_mirror_refuses_offset_without_file(tmp_path):
    """A manifest that records preds bytes while preds.jsonl is gone
    must refuse (same discipline as sink/manifest mismatches), not
    silently rebuild a mirror that starts mid-dataset."""
    from pytorch_vit_paper_replication_tpu.serve.offline import PredsJsonl

    with pytest.raises(ValueError, match="missing"):
        PredsJsonl(tmp_path / "preds.jsonl", resume_bytes=500)
    # offset 0 (killed before the first checkpoint) restarts cleanly
    p = PredsJsonl(tmp_path / "preds.jsonl", resume_bytes=0)
    p.write(0, np.asarray([[0.2, 0.8]], np.float32))
    assert p.flush() > 0
    p.close()


def test_npy_sink_refuses_mismatched_resume(tmp_path):
    sink = NpySink(tmp_path / "o.npy", rows=4, dim=3)
    sink.write(0, np.ones((2, 3), np.float32))
    sink.close()
    with pytest.raises(ValueError, match="delete"):
        NpySink(tmp_path / "o.npy", rows=4, dim=5, resume=True)
    again = NpySink(tmp_path / "o.npy", rows=4, dim=3, resume=True)
    out = np.array(again._map)
    again.close()
    np.testing.assert_array_equal(out[:2], np.ones((2, 3), np.float32))


def test_npy_sink_tensor_rows_and_row_shape_pinning(tmp_path):
    """ISSUE 19 satellite: NpySink takes a per-row SHAPE, not just a
    width — a [T, D] token-grid sink round-trips through resume, a
    2-D reopen of it refuses, and the manifest's row_shape pin keeps
    an out_dim-ambiguous tensor sink from resuming as a vector one."""
    sink = NpySink(tmp_path / "o.npy", rows=4, dim=(2, 3))
    sink.write(1, np.full((2, 2, 3), 7.0, np.float32))
    sink.close()
    assert np.load(tmp_path / "o.npy").shape == (4, 2, 3)
    # same trailing axis, different rank: refuse
    with pytest.raises(ValueError, match="delete"):
        NpySink(tmp_path / "o.npy", rows=4, dim=3, resume=True)
    again = NpySink(tmp_path / "o.npy", rows=4, dim=(2, 3), resume=True)
    np.testing.assert_array_equal(
        np.array(again._map[1:3]), np.full((2, 2, 3), 7.0, np.float32))
    again.close()

    # Manifest side of the same confusion: a tensor-row job pins
    # row_shape; a job with the same out_dim but different row shape
    # (or a vector job resuming a tensor sink) refuses with guidance.
    manifest = {"fingerprint": "fp", "head": "features",
                "total_records": 4, "out_dim": 3, "batch_size": 8,
                "ladder": [8], "row_shape": [2, 3], "records_done": 4}
    want = dict(fingerprint="fp", head="features", total_records=4,
                out_dim=3, batch_size=8, ladder=[8])
    assert validate_progress(manifest, **want, row_shape=(2, 3)) == 4
    with pytest.raises(ValueError, match="row_shape mismatch"):
        validate_progress(manifest, **want, row_shape=(4, 3))
    # vector jobs (rank-1 rows) don't pin the key — their manifests
    # stay byte-compatible with pre-tensor-row sinks
    assert validate_progress(
        {**manifest, "row_shape": None}, **want, row_shape=(3,)) == 4


# ------------------------------------------------- correctness + sharding
def test_offline_probs_bit_identical_to_predict_image(tiny_model,
                                                      tiny_pack, tmp_path):
    """ISSUE 8 satellite (a): the sharded, bucketed, double-buffered
    sweep produces EXACTLY the rows a predict_image loop produces —
    including the final 13 % 8 = 5-record chunk whose 3 pad rows must
    never leak into the sink."""
    model, params = tiny_model
    ds = PackedShardDataset(tiny_pack,
                            eval_center_transform(32, normalize=False),
                            startup_readahead=False)
    eng = OfflineEngine(model, params, head="probs", image_size=32,
                        buckets=(1, 4, 8))
    summary = eng.run(ds, tmp_path / "out", batch_size=8,
                      checkpoint_every_records=8, log_every_s=0)
    assert summary["processed"] == 13
    out = np.load(tmp_path / "out" / "outputs.npy")
    assert out.shape == (13, 3)        # exactly n rows — no pad leakage
    for i in range(13):
        row, _ = ds[i]
        _, _, ref = predict_image(model, params, row)
        np.testing.assert_array_equal(out[i], ref)
    manifest = load_progress(tmp_path / "out")
    assert manifest["records_done"] == manifest["rows_written"] == 13


def test_offline_features_head_pooled_embeddings(tiny_model, tiny_config,
                                                 tiny_pack, tmp_path):
    """--head features: the FeatureExtractor behind the same ladder
    emits pooled [D] rows equal to a direct backbone apply."""
    model, params = tiny_model
    cfg = tiny_config
    ds = PackedShardDataset(tiny_pack,
                            eval_center_transform(32, normalize=False),
                            startup_readahead=False)
    eng = OfflineEngine(model, params, head="features", image_size=32,
                        buckets=(8,))
    eng.run(ds, tmp_path / "out", batch_size=8, log_every_s=0)
    out = np.load(tmp_path / "out" / "outputs.npy")
    assert out.shape == (13, cfg.embedding_dim)
    backbone = ViTFeatureExtractor(cfg)
    fwd = jax.jit(lambda x: backbone.apply(
        {"params": params["backbone"]}, x))
    for i in (0, 7, 12):
        row, _ = ds[i]
        tokens = fwd(jnp.asarray(row)[None])
        ref = (tokens[:, 0] if cfg.pool == "cls" else
               tokens.mean(axis=1)).astype(jnp.float32)
        np.testing.assert_array_equal(out[i], np.asarray(ref)[0])


def test_offline_logits_head_bit_identical_presoftmax(tiny_model,
                                                      tiny_pack, tmp_path):
    """ISSUE 19 tentpole pin: --head logits is the probs expression
    MINUS the softmax — the pre-softmax classifier activations,
    bit-identical to a direct ``model.apply`` per row, and
    softmax(logits row) == probs row bit-for-bit (the probs head
    applies jax.nn.softmax to exactly these activations), so a logits
    sweep IS a valid distillation dataset for the probs the cascade
    serves."""
    model, params = tiny_model
    ds = PackedShardDataset(tiny_pack,
                            eval_center_transform(32, normalize=False),
                            startup_readahead=False)
    for head in ("logits", "probs"):
        eng = OfflineEngine(model, params, head=head, image_size=32,
                            buckets=(1, 4, 8))
        eng.run(ds, tmp_path / head, batch_size=8, log_every_s=0)
    logits = np.load(tmp_path / "logits" / "outputs.npy")
    probs = np.load(tmp_path / "probs" / "outputs.npy")
    assert logits.shape == (13, 3)
    fwd = jax.jit(lambda p, x: model.apply(
        {"params": p}, x).astype(jnp.float32))
    soft = jax.jit(lambda z: jax.nn.softmax(z, axis=-1))
    for i in (0, 7, 12):
        row, _ = ds[i]
        # padded-rung batch slice == direct single-image apply, and
        # softmax over the sink row reproduces the probs sink row.
        ref = np.asarray(fwd(params, jnp.asarray(row)[None]))[0]
        np.testing.assert_array_equal(logits[i], ref)
        np.testing.assert_array_equal(
            np.asarray(soft(jnp.asarray(logits[i]))), probs[i])
    # a logits manifest refuses a probs resume (identity axis pinned)
    manifest = load_progress(tmp_path / "logits")
    with pytest.raises(ValueError, match="mismatch"):
        validate_progress(manifest, fingerprint=manifest["fingerprint"],
                          head="probs", total_records=13, out_dim=3,
                          batch_size=8, ladder=manifest["ladder"])


def test_sharded_dispatch_spans_all_devices(tiny_model, devices):
    """ISSUE 8 satellite (c): on the 8-virtual-device CPU mesh the
    engine's ladder is rounded to device multiples, inputs really
    land one shard per device, and sharded outputs still match the
    unsharded single-image path."""
    model, params = tiny_model
    eng = OfflineEngine(model, params, head="probs", image_size=32,
                        buckets=(1, 4, 8))
    assert int(eng.mesh.devices.size) == 8
    assert eng.ladder == (8,)
    assert all(b % 8 == 0 for b in eng.ladder)
    x = eng.put(np.zeros((8, 32, 32, 3), np.float32))
    assert len(x.sharding.device_set) == 8
    shard_devs = {s.device for s in x.addressable_shards}
    assert shard_devs == set(devices)
    assert all(s.data.shape == (1, 32, 32, 3)
               for s in x.addressable_shards)
    imgs = np.asarray(
        jax.random.uniform(jax.random.key(3), (8, 32, 32, 3)), np.float32)
    got = np.asarray(eng.dispatch(imgs))
    for i in range(8):
        _, _, ref = predict_image(model, params, imgs[i])
        np.testing.assert_array_equal(got[i], ref)


# ------------------------------------------------------------- resumption
def test_resume_rewrites_tail_byte_identical(tiny_model, tiny_pack,
                                             tmp_path):
    """Resume semantics in-process: a manifest pointing mid-run (with
    garbage in the sink tail and junk appended to the preds mirror —
    what a SIGKILL between checkpoint and completion leaves behind)
    is picked up and the finished outputs are byte-identical to an
    uninterrupted run's."""
    model, params = tiny_model
    ds = PackedShardDataset(tiny_pack,
                            eval_center_transform(32, normalize=False),
                            startup_readahead=False)

    def engine():
        return OfflineEngine(model, params, head="probs", image_size=32,
                             buckets=(1, 4, 8), class_names=["a", "b", "c"])

    clean = tmp_path / "clean"
    engine().run(ds, clean, batch_size=8, checkpoint_every_records=8,
                 preds_jsonl=True, log_every_s=0)
    clean_sha = sink_sha256(clean / "outputs.npy")

    # Forge the post-SIGKILL state at records_done=8.
    wreck = tmp_path / "wreck"
    shutil.copytree(clean, wreck)
    preds_8 = b"".join(
        (clean / "preds.jsonl").read_bytes().splitlines(True)[:8])
    manifest = json.loads((wreck / PROGRESS_MANIFEST).read_text())
    manifest.update(records_done=8, rows_written=8,
                    preds_bytes=len(preds_8))
    write_progress(wreck, manifest)
    m = np.lib.format.open_memmap(wreck / "outputs.npy", mode="r+")
    m[8:] = np.float32(7.0)        # torn tail the resume must rewrite
    m.flush()
    del m
    with open(wreck / "preds.jsonl", "ab") as f:
        f.write(b'{"torn": true')  # unflushed partial line

    summary = engine().run(ds, wreck, batch_size=8,
                           checkpoint_every_records=8, preds_jsonl=True,
                           log_every_s=0)
    assert summary["resumed_from"] == 8
    assert summary["processed"] == 5
    assert sink_sha256(wreck / "outputs.npy") == clean_sha
    assert (wreck / "preds.jsonl").read_bytes() == \
        (clean / "preds.jsonl").read_bytes()

    # A completed job resumes as a no-op.
    again = engine().run(ds, wreck, batch_size=8, log_every_s=0)
    assert again.get("already_complete") and again["processed"] == 0
    assert sink_sha256(wreck / "outputs.npy") == clean_sha


def test_resume_refuses_other_jobs_output_dir(tiny_model, tiny_pack,
                                              tmp_path):
    model, params = tiny_model
    ds = PackedShardDataset(tiny_pack,
                            eval_center_transform(32, normalize=False),
                            startup_readahead=False)
    eng = OfflineEngine(model, params, head="probs", image_size=32,
                        buckets=(8,))
    eng.run(ds, tmp_path / "out", batch_size=8, log_every_s=0)
    other = OfflineEngine(model, params, head="features", image_size=32,
                          buckets=(8,))
    with pytest.raises(ValueError, match="mismatch"):
        other.run(ds, tmp_path / "out", batch_size=8, log_every_s=0)
    # --fresh (resume=False) restarts the dir for the new job instead
    out = other.run(ds, tmp_path / "out", batch_size=8, resume=False,
                    log_every_s=0)
    assert out["processed"] == 13 and out["head"] == "features"


def test_kill_resume_subprocess_byte_identical(tmp_path):
    """ISSUE 8 satellite (b), the real thing: SIGKILL a batch_infer
    CLI subprocess mid-run, rerun the same command, and the final
    sink sha256 equals an unkilled run's (the committed-evidence
    harness, at test scale)."""
    bi = _load_tool("batch_infer")
    result = bi.run_kill_resume(tmp_path, records=384, batch_size=32,
                                throttle_s=0.1, kill_after_records=64,
                                timeout_s=240.0)
    assert result["identical"], result
    assert 0 < result["killed_at_records"] <= 384
    assert result["resumed_from"] >= 0


# ------------------------------------------------------------------- CLI
def test_cli_end_to_end_and_knob_wiring(tmp_path):
    """The CLI path: checkpoint -> sharded sweep -> sink + summary +
    preds mirror, with the PR 1 page-cache knobs exposed on the
    inference path (defaults on) and re-invocation resuming to a
    no-op."""
    bi = _load_tool("batch_infer")
    job = bi._make_tiny_job(tmp_path, records=24)
    out = tmp_path / "out"
    args = [str(job["pack"]), "--checkpoint", str(job["checkpoint"]),
            "--num-classes", "3", "--preset", "ViT-Ti/16",
            "--out", str(out), "--batch-size", "8", "--preds-jsonl",
            "--sha256"]
    summary = bi.main(args)
    assert summary["processed"] == 24
    assert Path(summary["sink"]).exists()
    assert (out / "summary.json").is_file()
    assert len((out / "preds.jsonl").read_text().splitlines()) == 24
    probs = np.load(out / "outputs.npy")
    assert probs.shape == (24, 3)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)
    # resume: same command is a no-op continuation
    again = bi.main(args)
    assert again.get("already_complete")


def test_loader_knobs_reach_eval_and_train_paths(tmp_path):
    """The small-fix satellite: evict_behind now flows through
    create_packed_dataloaders (both loaders) and train.py exposes
    --evict-behind."""
    sc = _load_tool("scale_epoch")
    pack = sc.make_synthetic_pack(tmp_path / "p", records=8, pack_size=32,
                                  num_classes=2, records_per_shard=8,
                                  seed=0)
    from pytorch_vit_paper_replication_tpu.data.imagenet import (
        create_packed_dataloaders)
    train_dl, test_dl, _ = create_packed_dataloaders(
        pack, pack, image_size=32, batch_size=4, readahead=2,
        evict_behind=True, num_workers=1)
    assert train_dl.evict_behind and test_dl.evict_behind
    assert train_dl.readahead == 2 and test_dl.readahead == 2

    # Cheap flag-existence probe (running train's parser would build
    # the full 60-flag CLI): the source must expose the knob.
    src = (REPO / "pytorch_vit_paper_replication_tpu"
           / "train.py").read_text()
    assert "--evict-behind" in src
